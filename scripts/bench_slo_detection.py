"""SLO detection: time-to-detect + postmortem completeness, measured.

The ISSUE-5 acceptance bar: when a PR-3 chaos scenario fires against a
real gateway+replica fleet, the SLO engine must reach the ``page``
alert state within the slow-window bound, and the postmortem bundle the
trigger emits must contain the trace id of at least one offending
request — the full loop from signals → judgement → forensics.

Four replayed scenarios (the client-visible variants of the PR-3
matrix — detection needs failures the SLO surfaces can see):

- ``deadline_storm``      every request carries a 1 ms budget → replica
                          edge 504s → availability burn → page
- ``replica_crash``       the only replica is SIGKILLed mid-load →
                          gateway 5xx until the supervisor restarts it
- ``device_error_burst``  seeded chaos kills device.compute for a
                          bounded burst → predict 503s
- ``store_outage``        seeded chaos kills every store call → the
                          store-dependency objective burns (client
                          responses stay 200/degraded: the journal
                          works — which is exactly why the dependency
                          SLO exists)

Per scenario the harness boots a real fleet (supervisor + worker
process + in-process gateway), runs a healthy phase, injects at a
recorded instant, and polls ``/api/slo?replicas=1`` until any
objective pages. It then waits for the scenario's postmortem bundle
(worker- or gateway-side, per where the trigger lives) and checks the
offending trace ids — collected from failed/degraded responses'
``X-Trace-Id`` headers — against the bundle's request ring.

Writes ``artifacts/slo_detection.json``.

Usage: python scripts/bench_slo_detection.py [--quick]
       [--scenarios name ...] [--out artifacts/slo_detection.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")

PREDICT_BODY = {"summary": {"distance": 8000}, "weather": "Sunny",
                "traffic": "Medium", "driver_age": 35,
                "pickup_time": "2026-08-04T18:00:00"}

ROUTE_BODY = {
    "source_point": {"lat": 14.5836, "lon": 121.0409},
    "destination_points": [
        {"lat": 14.5507, "lon": 121.0262, "payload": 1}],
    "driver_details": {"driver_name": "slo-bench", "vehicle_type": "car",
                       "vehicle_capacity": 100,
                       "maximum_distance": 300000, "driver_age": 31},
    "meta": {"origin_id": "o-slo", "destination_ids": ["d1"]},
}

# Device-burst chaos: prob/seed chosen so the PER-POINT seeded draw
# sequence leaves the boot-time model self-check and warmup predict
# un-faulted (draws 1-2 ≥ prob) and then fails ~60% of the burst
# (determinism is the chaos layer's contract — same (spec, seed), same
# sequence).
DEVICE_SPEC = "device.compute:error=0.6@25"
DEVICE_SEED = 9

SLOW_WINDOW_BOUND_S = 3600.0  # the acceptance bound on time-to-detect


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(base, path, payload, headers=None, timeout=60.0):
    """→ (status, response headers dict, body dict)."""
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, dict(e.headers or {}), body
    except (urllib.error.URLError, OSError):
        return -1, {}, {}


def _get_json(base, path, timeout=15.0):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return json.loads(r.read())
    except (urllib.error.URLError, OSError, ValueError):
        return {}


def boot_fleet(recorder_dir: str, extra_env=None, warm: bool = True):
    """→ (supervisor, gateway, base_url). One real serving worker on
    the hermetic CPU backend behind an in-process gateway, with a fresh
    gateway-side flight recorder pointed at ``recorder_dir``."""
    from routest_tpu.core.config import FleetConfig, RecorderConfig
    from routest_tpu.obs.recorder import FlightRecorder, configure_recorder
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    # Fine-grained timeline windows for BOTH tiers: the scenarios run
    # tens of seconds, so 1 s frames are what makes the ISSUE-13
    # "bundle embeds the incident's timeline" assertion meaningful
    # (the production 10 s default would leave a --quick page bundle
    # with at most a frame or two). The in-process gateway reads
    # os.environ at serve() time, the workers inherit env.
    os.environ["RTPU_TIMELINE_RES"] = "1x600,10x360"
    configure_recorder(FlightRecorder(RecorderConfig(
        dir=os.path.join(recorder_dir, "gateway"), min_interval_s=0.0)))
    ports = [_free_port()]
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        "ROUTEST_WARM_BUCKETS": "0",
        "ROUTEST_MESH": "0",
        "ETA_MODEL_PATH": MODEL,
        "RTPU_RECORDER_DIR": os.path.join(recorder_dir, "workers"),
        "RTPU_RECORDER_MIN_INTERVAL_S": "0",
        "RTPU_TIMELINE_RES": "1x600,10x360",
    })
    env.update(extra_env or {})
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    if not sup.ready(timeout=300):
        sup.drain(timeout=10)
        raise RuntimeError("fleet worker never became ready")
    if warm:
        for port in ports:
            _post(f"http://127.0.0.1:{port}", "/api/predict_eta",
                  PREDICT_BODY)
    cfg = FleetConfig(eject_after=3, cooldown_s=1.0, max_inflight=32,
                      queue_depth=128, hedge=False)
    gw = Gateway([("127.0.0.1", p) for p in ports], cfg, supervisor=sup)
    httpd = gw.serve("127.0.0.1", 0)
    return sup, gw, f"http://127.0.0.1:{httpd.server_address[1]}"


def shutdown_fleet(sup, gw):
    from routest_tpu.obs.recorder import configure_recorder

    try:
        gw.drain(timeout=5)
    finally:
        sup.drain(timeout=15)
        configure_recorder(None)


class DetectionRun:
    """Shared scenario mechanics: a load thread, a /api/slo poller, an
    injection instant, and the offending-trace-id ledger."""

    def __init__(self, base: str, detect_timeout_s: float) -> None:
        self.base = base
        self.detect_timeout_s = detect_timeout_s
        self.offending: set = set()
        self.statuses: dict = {}
        self.t_inject: float = 0.0
        self.t_inject_wall: float = 0.0   # unix — timeline frames use it
        self.paged_at: float = 0.0
        self.page_objective: str = ""
        self.page_component: str = ""
        self._stop = threading.Event()

    def send(self, path, body, headers=None, offending_if=None):
        status, rh, resp = _post(self.base, path, body, headers=headers,
                                 timeout=30.0)
        self.statuses[status] = self.statuses.get(status, 0) + 1
        trace_id = rh.get("X-Trace-Id") or rh.get("x-trace-id")
        is_offending = (status >= 500 if offending_if is None
                        else offending_if(status, resp))
        if is_offending and trace_id:
            self.offending.add(trace_id)
        return status, resp

    def _poll_slo(self) -> None:
        while not self._stop.is_set():
            snap = _get_json(self.base, "/api/slo?replicas=1", timeout=10.0)
            candidates = [("gateway", snap)]
            for rid, rep in (snap.get("replica_slo") or {}).items():
                candidates.append((f"replica:{rid}", rep))
            for component, payload in candidates:
                for name, obj in (payload.get("objectives") or {}).items():
                    if obj.get("state") == "page":
                        self.paged_at = time.monotonic()
                        self.page_objective = name
                        self.page_component = component
                        self._stop.set()
                        return
            self._stop.wait(0.15)

    def detect(self, load_fn) -> None:
        """Run ``load_fn(self)`` (which must set ``t_inject``) while
        polling for the page edge; returns once paged or the overall
        timeout lapses. ``detect_timeout_s`` caps the whole scenario
        (healthy phase included) — the measured TTD is vs t_inject."""
        poller = threading.Thread(target=self._poll_slo, daemon=True)
        poller.start()
        loader = threading.Thread(target=load_fn, args=(self,),
                                  daemon=True)
        loader.start()
        self._stop.wait(self.detect_timeout_s + 60.0)
        self._stop.set()
        loader.join(timeout=30)
        poller.join(timeout=5)

    def summary(self) -> dict:
        ttd = (self.paged_at - self.t_inject) if self.paged_at else None
        return {
            "paged": bool(self.paged_at),
            "time_to_detect_s": round(ttd, 2) if ttd is not None else None,
            "slow_window_bound_s": SLOW_WINDOW_BOUND_S,
            "within_bound": bool(self.paged_at
                                 and ttd <= SLOW_WINDOW_BOUND_S),
            "page_objective": self.page_objective,
            "page_component": self.page_component,
            "offending_traces_observed": len(self.offending),
            "statuses": {str(k): v
                         for k, v in sorted(self.statuses.items())},
        }


def _wait_bundle_with_offender(dirs, offending, timeout_s=30.0):
    """Scan postmortem dirs until some bundle's requests.jsonl contains
    an offending trace id → (bundle_name, matched_count) or (None, 0)."""
    deadline = time.monotonic() + timeout_s
    best = (None, 0)
    while time.monotonic() < deadline:
        bundles = []
        for root in dirs:
            if not os.path.isdir(root):
                continue
            bundles.extend(os.path.join(root, d)
                           for d in sorted(os.listdir(root))
                           if d.startswith("pm_"))
        for bundle in bundles:
            req_path = os.path.join(bundle, "requests.jsonl")
            if not os.path.exists(req_path):
                continue
            try:
                with open(req_path) as f:
                    ids = {json.loads(line).get("trace_id")
                           for line in f if line.strip()}
            except (OSError, ValueError):
                continue
            matched = len(ids & offending)
            if matched:
                return os.path.basename(bundle), matched
            best = (os.path.basename(bundle), 0)
        time.sleep(0.5)
    return best


def _scenario(name, args, extra_env=None, warm=True):
    """Context: boots the fleet with a fresh recorder dir; yields the
    pieces; always tears down."""
    recorder_dir = tempfile.mkdtemp(prefix=f"slo-bench-{name}-")
    sup, gw, base = boot_fleet(recorder_dir, extra_env=extra_env,
                               warm=warm)
    return recorder_dir, sup, gw, base


def _page_bundle_timelines(dirs, t_inject_wall, timeout_s=30.0):
    """ISSUE-13: every ``slo_page*`` bundle must embed a NON-EMPTY
    timeline slice, and the scenario's page bundles together must cover
    the incident (≥1 frame whose window ends at/after the injection
    instant — the follow-up bundle guarantees one exists). → dict of
    the assertion results."""
    deadline = time.monotonic() + timeout_s
    result = {"page_bundles": 0, "page_bundles_with_timeline": 0,
              "timeline_frames": 0, "timeline_covers_incident": False}
    while time.monotonic() < deadline:
        bundles = []
        for root in dirs:
            if not os.path.isdir(root):
                continue
            bundles.extend(os.path.join(root, d)
                           for d in sorted(os.listdir(root))
                           if d.startswith("pm_"))
        page_bundles = []
        for bundle in bundles:
            try:
                manifest = json.load(
                    open(os.path.join(bundle, "manifest.json")))
            except (OSError, ValueError):
                continue  # racing an in-progress write
            if str(manifest.get("reason", "")).startswith("slo_page"):
                page_bundles.append(bundle)
        if page_bundles:
            result["page_bundles"] = len(page_bundles)
            result["page_bundles_with_timeline"] = 0
            result["timeline_frames"] = 0
            covers = False
            for bundle in page_bundles:
                try:
                    doc = json.load(
                        open(os.path.join(bundle, "timeline.json")))
                except (OSError, ValueError):
                    continue
                frames = [f for comp in doc.values()
                          for f in comp.get("frames", [])]
                if frames:
                    result["page_bundles_with_timeline"] += 1
                    result["timeline_frames"] += len(frames)
                if any(f["t"] >= t_inject_wall for f in frames):
                    covers = True
            result["timeline_covers_incident"] = covers
            if covers and result["page_bundles_with_timeline"] \
                    == result["page_bundles"]:
                return result
        time.sleep(0.5)
    return result


def _finish(run, recorder_dir, bundles_extra=None):
    out = run.summary()
    dirs = [os.path.join(recorder_dir, "workers"),
            os.path.join(recorder_dir, "gateway")]
    bundle, matched = _wait_bundle_with_offender(
        dirs, run.offending, timeout_s=30.0)
    out["bundle"] = bundle
    out["bundle_offending_traces"] = matched
    out["bundle_has_offender"] = matched > 0
    timeline = _page_bundle_timelines(dirs, run.t_inject_wall)
    out.update(timeline)
    out["bundle_has_timeline"] = bool(
        timeline["page_bundles"]
        and timeline["page_bundles_with_timeline"]
        == timeline["page_bundles"]
        and timeline["timeline_covers_incident"])
    out["pass"] = bool(out["paged"] and out["within_bound"]
                       and out["bundle_has_offender"]
                       and out["bundle_has_timeline"])
    if bundles_extra:
        out.update(bundles_extra)
    shutil.rmtree(recorder_dir, ignore_errors=True)
    return out


def scenario_deadline_storm(args):
    recorder_dir, sup, gw, base = _scenario("deadline_storm", args)
    try:
        run = DetectionRun(base, args.detect_timeout)

        def load(run):
            for _ in range(args.healthy_n):
                run.send("/api/predict_eta", PREDICT_BODY)
            run.t_inject = time.monotonic()
            run.t_inject_wall = time.time()
            i = 0
            while not run._stop.is_set():
                # unique rows per request: the fast-lane cache would
                # otherwise answer a repeated body inside ANY budget —
                # correctly, but a storm of doomed work is the point
                i += 1
                body = {**PREDICT_BODY,
                        "summary": {"distance": 8000 + i}}
                run.send("/api/predict_eta", body,
                         headers={"X-Deadline-Ms": "1"},
                         offending_if=lambda s, _b: s == 504)

        run.detect(load)
        out = _finish(run, recorder_dir)
        out["description"] = ("every post-injection request carries a "
                              "1 ms budget over unique rows; batcher/"
                              "edge 504s burn the availability "
                              "objective")
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_replica_crash(args):
    recorder_dir, sup, gw, base = _scenario("replica_crash", args)
    try:
        run = DetectionRun(base, args.detect_timeout)

        def load(run):
            for _ in range(args.healthy_n):
                run.send("/api/predict_eta", PREDICT_BODY)
            run.t_inject = time.monotonic()
            run.t_inject_wall = time.time()
            sup.kill_replica(0)
            while not run._stop.is_set():
                run.send("/api/predict_eta", PREDICT_BODY)
                time.sleep(0.02)

        run.detect(load)
        out = _finish(run, recorder_dir)
        out["restarts"] = sup.snapshot()["r0"]["restarts"]
        out["description"] = ("the only replica is SIGKILLed; gateway "
                              "5xx until the supervisor restarts it")
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_device_error_burst(args):
    recorder_dir, sup, gw, base = _scenario(
        "device_error_burst", args,
        extra_env={"RTPU_CHAOS_SPEC": DEVICE_SPEC,
                   "RTPU_CHAOS_SEED": str(DEVICE_SEED)})
    try:
        run = DetectionRun(base, args.detect_timeout)

        def load(run):
            # healthy phase on a non-device endpoint: the seeded burst
            # budget must not leak into the baseline
            for _ in range(args.healthy_n):
                run.send("/api/update_tracker", {"route_id": "x"})
            run.t_inject = time.monotonic()
            run.t_inject_wall = time.time()
            i = 0
            while not run._stop.is_set():
                # unique rows: repeated bodies would be answered by the
                # fast-lane cache without ever touching the device
                i += 1
                run.send("/api/predict_eta",
                         {**PREDICT_BODY,
                          "summary": {"distance": 8000 + i}})
                time.sleep(0.01)

        run.detect(load)
        out = _finish(run, recorder_dir)
        out["chaos"] = {"spec": DEVICE_SPEC, "seed": DEVICE_SEED}
        out["description"] = ("seeded chaos errors ~60% of device "
                              "scoring calls for a bounded burst; "
                              "predict 503s page availability")
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_store_outage(args):
    recorder_dir, sup, gw, base = _scenario(
        "store_outage", args,
        extra_env={"RTPU_CHAOS_SPEC": "store.http:error=1.0@60",
                   "RTPU_CHAOS_SEED": "7",
                   "RTPU_STORE_RETRIES": "1",
                   "RTPU_STORE_BREAKER_AFTER": "2",
                   "RTPU_STORE_COOLDOWN_S": "5"})
    try:
        run = DetectionRun(base, args.detect_timeout)

        def degraded_or_5xx(status, body):
            props = (body or {}).get("properties") or {}
            return status >= 500 or bool(props.get("degraded"))

        def load(run):
            # healthy phase off the store path
            for _ in range(args.healthy_n):
                run.send("/api/predict_eta", PREDICT_BODY)
            run.t_inject = time.monotonic()
            run.t_inject_wall = time.time()
            while not run._stop.is_set():
                run.send("/api/optimize_route", ROUTE_BODY,
                         offending_if=degraded_or_5xx)

        run.detect(load)
        out = _finish(run, recorder_dir)
        out["description"] = ("every store call fails; writes journal "
                              "(client 200/degraded) while the "
                              "store-dependency objective burns — the "
                              "page fires with ZERO client 5xx, which "
                              "is the point of a dependency SLO")
        return out
    finally:
        shutdown_fleet(sup, gw)


SCENARIOS = {
    "deadline_storm": scenario_deadline_storm,
    "replica_crash": scenario_replica_crash,
    "device_error_burst": scenario_device_error_burst,
    "store_outage": scenario_store_outage,
}


def main() -> None:
    from routest_tpu.utils.logging import get_logger

    log = get_logger("routest_tpu.bench_slo_detection")
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="shorter phases and timeouts")
    parser.add_argument("--scenarios", nargs="*", default=None,
                        choices=sorted(SCENARIOS))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "slo_detection.json"))
    args = parser.parse_args()
    args.healthy_n = 10 if args.quick else 25
    args.detect_timeout = 45.0 if args.quick else 90.0

    results = {}
    for name in (args.scenarios or list(SCENARIOS)):
        log.info("slo_scenario_started", scenario=name)
        t0 = time.time()
        try:
            results[name] = SCENARIOS[name](args)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}",
                             "pass": False}
            log.error("slo_scenario_failed", scenario=name,
                      error=f"{type(e).__name__}: {e}")
        results[name]["wall_s"] = round(time.time() - t0, 1)
        log.info("slo_scenario_finished", scenario=name,
                 ok=results[name].get("pass"),
                 ttd_s=results[name].get("time_to_detect_s"),
                 wall_s=results[name]["wall_s"])

    record = {
        "generated_unix": int(time.time()),
        "host": {"cpu_count": os.cpu_count(), "platform": sys.platform},
        "slo_defaults": {"fast_window_s": 300.0, "slow_window_s": 3600.0,
                         "page_burn": 14.4, "tick_s": 1.0},
        "note": ("time-to-detect = fault injection → first objective in "
                 "the page state (polled at 150 ms); the slow-window "
                 "bound is the acceptance ceiling, the measured values "
                 "are seconds because burn-rate windows shorter than "
                 "the process lifetime evaluate on available history."),
        "scenarios": results,
        "all_pass": all(r.get("pass") for r in results.values()),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    log.info("slo_detection_written", path=args.out,
             all_pass=record["all_pass"])
    print(json.dumps(record, indent=2))


if __name__ == "__main__":
    main()
