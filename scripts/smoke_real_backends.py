"""Opt-in smoke test against REAL SaaS backends (VERDICT r3 #9).

Every PostgREST and Redis code path in this repo is proven against
in-repo fakes (``tests/fake_postgrest.py``, ``serve/netbus.py``) because
the build sandbox has zero egress. The reference runs against live
Supabase/Upstash (``Flaskr/routes.py:15-23``, ``Flaskr/__init__.py:25``)
— this script is the missing integration rung for operators who DO have
credentials: point it at real services and it drives the same client
classes the server uses, read-after-write verified, cleaning up after
itself.

Usage (each section runs only when its env vars are set; otherwise it
reports SKIP and exits 0 so CI without credentials stays green):

    SUPABASE_URL=https://<proj>.supabase.co \
    SUPABASE_SERVICE_ROLE_KEY=<service-role-key> \
    REDIS_URL=rediss://default:<password>@<host>:6380 \
    python scripts/smoke_real_backends.py

Exit status: 0 = every attempted section passed (or all skipped),
1 = any attempted section failed.
"""

from __future__ import annotations

import json
import os
import sys
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smoke_postgrest(url: str, key: str) -> dict:
    """insert → get → list(+engine filter) → delete → verify gone, via
    the server's own PostgRESTStore."""
    from routest_tpu.serve.store import PostgRESTStore

    store = PostgRESTStore(url, key)
    if not store.ping():
        return {"status": "fail", "error": "ping failed (url/key/table?)"}
    # origin_id is a NOT NULL FK onto locations: prefer a row that
    # actually exists in the target DB, falling back to the
    # deterministic seed ids (schema.sql + data/locations.py mirror the
    # reference's seeder, so a seeded Supabase has them).
    try:
        r = store._requests_lib.get(
            f"{store._rest}/locations?select=id&limit=1",
            headers=store._headers, timeout=store._timeout)
        origin_id = (r.json() or [{}])[0].get("id") if r.ok else None
    except Exception:
        origin_id = None
    if not origin_id:
        from routest_tpu.data.locations import locations_table

        origin_id = locations_table()[0]["id"]
    marker = f"smoke-{uuid.uuid4()}"
    req_id = None
    try:
        req_id = store.insert_request({
            "origin_id": origin_id,
            "stops": {"destination_ids": [],
                      "destination_points": [{"lat": 14.58, "lon": 121.04}]},
            "status": "completed",
            "engine": "smoke_real_backends",
            "vehicle_id": marker,
            "driver_age": 30,
        })
        store.insert_result({
            "request_id": req_id,
            "total_distance": 1.0,
            "total_duration": 2.0,
            "optimized_order": [0],
            "legs": [],
            "geometry": {"type": "LineString", "coordinates": []},
            "eta_minutes_ml": None,
        })
        got = store.get_request(req_id)
        if not got or got.get("vehicle_id") != marker:
            return {"status": "fail", "error": "read-after-write mismatch",
                    "request_id": req_id, "got": got}
        hist = store.list_history(limit=5, engine="smoke_real_backends")
        if not any(h.get("id") == req_id for h in hist):
            return {"status": "fail",
                    "error": "engine-filtered history missed the row",
                    "request_id": req_id}
        return {"status": "ok", "request_id": req_id}
    finally:
        if req_id is not None:
            deleted = store.delete_request(req_id)
            if store.get_request(req_id) is not None:
                print(f"  WARNING: cleanup left row {req_id} "
                      f"(delete={deleted})", file=sys.stderr)


def smoke_redis(url: str) -> dict:
    """publish → subscribe round trip via the server's own RedisBus."""
    from routest_tpu.serve.bus import RedisBus

    bus = RedisBus(url)
    if not bus.ping():
        return {"status": "fail", "error": "redis ping failed"}
    channel = f"smoke:{uuid.uuid4()}"
    payload = {"smoke": True, "ts": time.time()}
    with bus.subscribe(channel) as sub:
        time.sleep(0.5)  # pubsub registration races the first publish
        bus.publish(channel, payload)
        deadline = time.time() + 10
        msg = None
        while msg is None and time.time() < deadline:
            msg = sub.get(timeout=1.0)
    if not (isinstance(msg, dict) and msg.get("smoke") is True):
        return {"status": "fail", "error": f"payload mismatch: {msg!r}"}
    return {"status": "ok"}


def main() -> int:
    sections = {}
    url = os.environ.get("SUPABASE_URL")
    key = os.environ.get("SUPABASE_SERVICE_ROLE_KEY")
    if url and key:
        print("PostgREST: driving real backend…", flush=True)
        try:
            sections["postgrest"] = smoke_postgrest(url, key)
        except Exception as e:  # noqa: BLE001 - smoke report, not a crash
            sections["postgrest"] = {"status": "fail",
                                     "error": f"{type(e).__name__}: {e}"}
    else:
        sections["postgrest"] = {
            "status": "skip",
            "reason": "SUPABASE_URL / SUPABASE_SERVICE_ROLE_KEY not set"}

    redis_url = os.environ.get("REDIS_URL")
    if redis_url and redis_url.startswith(("redis://", "rediss://")):
        print("Redis: driving real backend…", flush=True)
        try:
            sections["redis"] = smoke_redis(redis_url)
        except Exception as e:  # noqa: BLE001
            sections["redis"] = {"status": "fail",
                                 "error": f"{type(e).__name__}: {e}"}
    else:
        sections["redis"] = {"status": "skip",
                             "reason": "REDIS_URL not set (redis:// or "
                                       "rediss://)"}

    print(json.dumps(sections, indent=2))
    return 1 if any(s["status"] == "fail" for s in sections.values()) else 0


if __name__ == "__main__":
    sys.exit(main())
