"""Observability overhead: everything-off vs sampled vs always-on vs
the ISSUE-13 telemetry layer (timeline+watcher, tail sampling).

The acceptance bar (ISSUE 2, re-pinned by ISSUE 5 with the SLO engine
and flight recorder in the stack, and by ISSUE 13 with the timeline)
is that the always-on posture costs ≤5% on the ``load_test``
predict_eta p95. This script measures it honestly: identical server
subprocesses (the same spawn-and-wait pattern as
``scripts/load_test.py``), differing ONLY in env:

- ``off``       — tracing, flight recorder, SLO engine, AND timeline
                  disabled (``RTPU_OBS_TRACE=0 RTPU_RECORDER=0
                  RTPU_SLO=0 RTPU_TIMELINE=0``) — the true baseline;
- ``sampled``   — trace sampling 0.1, recorder+SLO+timeline on
                  (production default posture);
- ``always_on`` — trace sampling 1.0, recorder+SLO+timeline on (every
                  request traced, recorded, rolled into burn rates,
                  and ticked into the timeline rings);
- ``timeline``  — ONLY the timeline store + anomaly watcher on, over
                  the off baseline (isolates the ticker's cost);
- ``tail``      — the always-on posture plus tail-based trace
                  retention (``RTPU_TAIL_SAMPLE=1`` — every trace
                  buffers; the decision moves to root completion).

Each mode runs the load_test single-row phase (the per-request-overhead-
dominated endpoint: tiny payloads, so any observability cost is
maximally visible) plus a batch phase, and the report lands in
``artifacts/obs_overhead.json``. On a 1-core host client and server
time-share, so run-to-run noise of a few percent is expected — the
artifact records all absolute numbers, not just the ratio.

Usage: python scripts/bench_obs_overhead.py [--threads 8] [--requests 40]
       [--quick] [--out artifacts/obs_overhead.json]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_load_test():
    spec = importlib.util.spec_from_file_location(
        "rtpu_load_test", os.path.join(REPO, "scripts", "load_test.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_server(env_overrides: dict) -> tuple:
    import tempfile

    port = _free_port()
    env = dict(os.environ)
    env.update({"PORT": str(port), "ROUTEST_FORCE_CPU": "1",
                # bundles (if any trigger fires mid-bench) go to a
                # throwaway dir, not the repo's artifacts/
                "RTPU_RECORDER_DIR": tempfile.mkdtemp(prefix="obs-bench-")})
    env.update(env_overrides)
    proc = subprocess.Popen([sys.executable, "-m", "routest_tpu.serve"],
                            env=env, cwd=REPO)
    return proc, f"http://127.0.0.1:{port}"


def _wait_ready(lt, proc, base: str, timeout: float = 300.0) -> None:
    deadline = time.time() + timeout
    while True:
        try:
            if lt._get(base, "/api/ping", timeout=2).get("ok"):
                return
        except Exception:
            pass
        if proc.poll() is not None:
            raise RuntimeError("server process died during boot")
        if time.time() > deadline:
            proc.kill()
            raise RuntimeError("server never became ready")
        time.sleep(0.5)


MODES = (
    ("off", {"RTPU_OBS_TRACE": "0", "RTPU_RECORDER": "0",
             "RTPU_SLO": "0", "RTPU_TIMELINE": "0",
             "RTPU_TAIL_SAMPLE": "0", "RTPU_EFF": "0",
             "RTPU_LEDGER": "0"}),
    ("sampled", {"RTPU_OBS_TRACE": "1", "RTPU_OBS_SAMPLE": "0.1",
                 "RTPU_RECORDER": "1", "RTPU_SLO": "1",
                 "RTPU_TIMELINE": "1"}),
    ("always_on", {"RTPU_OBS_TRACE": "1", "RTPU_OBS_SAMPLE": "1.0",
                   "RTPU_RECORDER": "1", "RTPU_SLO": "1",
                   "RTPU_TIMELINE": "1"}),
    ("timeline", {"RTPU_OBS_TRACE": "0", "RTPU_RECORDER": "0",
                  "RTPU_SLO": "0", "RTPU_TIMELINE": "1",
                  "RTPU_TIMELINE_WATCH": "1"}),
    ("tail", {"RTPU_OBS_TRACE": "1", "RTPU_OBS_SAMPLE": "1.0",
              "RTPU_RECORDER": "1", "RTPU_SLO": "1",
              "RTPU_TIMELINE": "1", "RTPU_TAIL_SAMPLE": "1"}),
    # always_on minus the change ledger: isolates what recording
    # state changes costs (ring append + metric touch per change —
    # the hot request path records nothing) against the <=5% budget.
    ("ledger_off", {"RTPU_OBS_TRACE": "1", "RTPU_OBS_SAMPLE": "1.0",
                    "RTPU_RECORDER": "1", "RTPU_SLO": "1",
                    "RTPU_TIMELINE": "1", "RTPU_LEDGER": "0"}),
)


def run_mode(lt, env_overrides: dict, threads: int, requests: int,
             batch_size: int, repeats: int) -> dict:
    proc, base = _spawn_server(env_overrides)
    try:
        _wait_ready(lt, proc, base)
        # one untimed warmup sweep so every mode starts with hot buckets
        warm = lt.PersistentPoster(base)
        try:
            for _ in range(3):
                warm.post("/api/predict_eta",
                          {"summary": {"distance": 10_000}})
        finally:
            warm.close()
        # Best-of-N measured phases: on a 1-core host client and server
        # time-share, so a single run's p95 carries scheduler noise that
        # would swamp a few-percent tracing delta. The minimum is the
        # achievable latency; noise only inflates it.
        best, errors = None, 0
        for _ in range(max(1, repeats)):
            report, errs = lt.run_load([base], threads, requests)
            errors += len(errs)
            eta = report.get("predict_eta", {})
            if best is None or (eta.get("p95_ms") or 1e9) < \
                    (best["predict_eta"].get("p95_ms") or 1e9):
                best = {"predict_eta": eta, "rps": report.get("rps")}
        out = {**best, "errors": errors, "runs": max(1, repeats)}
        if batch_size > 0:
            batch_best = None
            for _ in range(max(1, repeats)):
                batch, berr = lt.run_batch_load([base], 2, 8, batch_size)
                out["errors"] += len(berr)
                if batch_best is None or (batch.get("preds_per_s") or 0) > \
                        (batch_best.get("preds_per_s") or 0):
                    batch_best = {k: batch.get(k) for k in
                                  ("preds_per_s", "p50_ms", "p95_ms")}
            out["predict_eta_batch"] = batch_best
        return out
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--requests", type=int, default=40,
                        help="single-row requests per client thread")
    parser.add_argument("--batch-size", type=int, default=2048,
                        help="rows per predict_eta_batch request "
                             "(0 skips the batch phase)")
    parser.add_argument("--repeats", type=int, default=3,
                        help="measured phases per mode; best-of-N "
                             "(noise only inflates latency)")
    parser.add_argument("--quick", action="store_true",
                        help="smaller phases (CI re-verification)")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "obs_overhead.json"))
    args = parser.parse_args()
    if args.quick:
        args.threads = min(args.threads, 4)
        args.requests = min(args.requests, 20)
        args.batch_size = min(args.batch_size, 1024)
        args.repeats = min(args.repeats, 2)

    lt = _load_load_test()
    results = {}
    # Two passes, second in reversed mode order, best-of merged per
    # mode: a sequential bench drifts (page cache, thermal, background
    # load), which systematically taxes whichever mode runs LAST —
    # measured at ~10% on the batch phase. Running both orders and
    # keeping each mode's best cancels the drift; real overhead
    # survives both orders.
    for mode_order in (MODES, tuple(reversed(MODES))):
        for name, env_overrides in mode_order:
            print(f"[obs_overhead] mode={name} …", file=sys.stderr)
            out = run_mode(lt, env_overrides, args.threads,
                           args.requests, args.batch_size, args.repeats)
            prev = results.get(name)
            if prev is not None:
                out["errors"] += prev["errors"]
                out["runs"] += prev["runs"]
                if (prev["predict_eta"].get("p95_ms") or 1e9) < \
                        (out["predict_eta"].get("p95_ms") or 1e9):
                    out["predict_eta"] = prev["predict_eta"]
                    out["rps"] = prev["rps"]
                pb, ob = (prev.get("predict_eta_batch") or {}), \
                    (out.get("predict_eta_batch") or {})
                if (pb.get("preds_per_s") or 0) > \
                        (ob.get("preds_per_s") or 0):
                    out["predict_eta_batch"] = pb
            results[name] = out
            print(f"[obs_overhead] {name}: "
                  f"{json.dumps(results[name].get('predict_eta', {}))}",
                  file=sys.stderr)

    def p95(mode: str):
        return results[mode].get("predict_eta", {}).get("p95_ms")

    report = {
        "modes": results,
        "threads": args.threads,
        "requests_per_thread": args.requests,
        "cpu_count": os.cpu_count(),
    }
    if p95("off") and p95("always_on"):
        overhead = (p95("always_on") - p95("off")) / p95("off") * 100.0
        report["p95_overhead_always_on_pct"] = round(overhead, 2)
        report["within_5pct_budget"] = bool(overhead <= 5.0)
    for mode in ("sampled", "timeline", "tail", "ledger_off"):
        if p95("off") and p95(mode):
            report[f"p95_overhead_{mode}_pct"] = round(
                (p95(mode) - p95("off")) / p95("off") * 100.0, 2)
    bo = results.get("off", {}).get("predict_eta_batch", {})
    ba = results.get("always_on", {}).get("predict_eta_batch", {})
    if bo.get("preds_per_s") and ba.get("preds_per_s"):
        report["batch_preds_per_s_delta_pct"] = round(
            (ba["preds_per_s"] - bo["preds_per_s"])
            / bo["preds_per_s"] * 100.0, 2)

    print(json.dumps(report, indent=2))
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"[obs_overhead] report → {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
