"""Binary wire serving end to end → artifacts/wire.json.

The ISSUE-19 acceptance scenario, measured on a real fleet:

- ``micro`` — one real worker (``python -m routest_tpu.serve``,
  wire channel armed) behind the in-process gateway. Gates: exact
  (bitwise) wire↔JSON parity through the gateway; ≥2× throughput
  over the JSON path on small (≤64-row) batches; gateway-added
  overhead (via-gateway wire p95 minus direct-channel p95) under
  1 ms; sustained ≥100k ETA rows/s through one gateway on 1024-row
  open-loop frames; and the channel actually carried the traffic
  (connection reuse ratio, not per-request HTTP).
- ``probe_parity`` — the bench_probing live fleet with the wire
  format armed: open-loop binary load while ≥1 legitimate metric
  flip and ≥1 verified model swap land, with the blackbox prober's
  ``wire`` kind watching. Gates: the wire parity probe stays green
  (``correctness:wire`` never pages) across both transitions.

Caches (street extract, hierarchy overlay, XLA compiles) are shared
across scenarios AND battery rounds via ``--cache-dir`` (default
``artifacts/bench_cache/wire``).

Usage: python scripts/bench_wire.py [--quick]
       [--out artifacts/wire.json] [--cache-dir DIR]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")
WIRE_CT = "application/x-rtpu-wire"

# Acceptance gates (ISSUE-19).
SPEEDUP_MIN = 2.0            # wire vs JSON rows/s, small batches
GW_OVERHEAD_P95_MS = 1.0     # via-gateway minus direct-channel
SUSTAINED_ROWS_PER_S = 100_000.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _bench_probing():
    spec = importlib.util.spec_from_file_location(
        "bench_probing", os.path.join(REPO, "scripts",
                                      "bench_probing.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _family_total(name: str, where=None) -> float:
    from routest_tpu.obs.registry import get_registry

    fam = get_registry().get(name)
    if fam is None:
        return 0.0
    total = 0.0
    for key, child in fam.items():
        if where is None or where(key):
            total += child.value
    return total


def _jsonable(o):
    import numpy as np

    if isinstance(o, (np.bool_,)):
        return bool(o)
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


def _p95_ms(lat_s) -> float:
    ok = sorted(lat_s)
    if not ok:
        return float("nan")
    return ok[min(len(ok) - 1, int(0.95 * len(ok)))] * 1000.0


# ── micro scenario ───────────────────────────────────────────────────


def _closed_loop(base: str, requests, duration_s: float,
                 workers: int = 4):
    """→ (ok_count, err_count, elapsed_s): keep-alive closed loop over
    a fixed request cycle — both formats pay the same client."""
    from routest_tpu.loadgen.engine import KeepAliveClient

    t0 = time.monotonic()
    stop_at = t0 + duration_s
    ok = [0] * workers
    err = [0] * workers

    def run(w: int) -> None:
        client = KeepAliveClient(base, timeout=30.0)
        i = w
        while time.monotonic() < stop_at:
            try:
                status, _ = client.send(requests[i % len(requests)])
            except Exception:
                status = -1
            if status == 200:
                ok[w] += 1
            else:
                err[w] += 1
            i += workers
        client.close()

    threads = [threading.Thread(target=run, args=(w,))
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(ok), sum(err), time.monotonic() - t0


def _parity_check(base: str) -> dict:
    """Golden body over both content-types through ``base`` — the
    prober's own bitwise compare, run once as a hard gate."""
    import numpy as np

    from routest_tpu.obs.prober import (eta_columns, golden_probe_body,
                                        golden_wire_frame, _http_json,
                                        _http_wire)
    from routest_tpu.serve import wirecodec as wc

    url = f"{base}/api/predict_eta_batch"
    payload, _ = _http_json("POST", url, golden_probe_body(), 60.0,
                            probe="")
    raw, _ = _http_wire(url, golden_wire_frame(), 60.0, probe="")
    wire = wc.decode_eta_response(raw)
    minutes = np.asarray(wire["minutes"], np.float64)
    finite = np.isfinite(minutes)
    got = {"eta_minutes_ml": np.where(finite, np.round(minutes, 4),
                                      np.nan)}
    for lvl, vals in wire["bands"].items():
        ok = finite & np.isfinite(np.asarray(vals))
        got[f"eta_minutes_ml_{lvl}"] = np.where(
            ok, np.round(vals, 4), np.nan)
    jcols = eta_columns(payload)
    cols_equal = sorted(got) == sorted(jcols) and all(
        got[k].tobytes() == jcols[k].tobytes() for k in jcols)
    iso = np.datetime_as_string(
        np.asarray(wire["completion_ms"],
                   np.int64).astype("datetime64[ms]"), unit="s")
    wire_iso = [str(s) if f else None for s, f in zip(iso, finite)]
    iso_equal = wire_iso == payload.get("eta_completion_time_ml")
    return {"rows": int(len(minutes)),
            "columns": sorted(got),
            "columns_bitwise_equal": bool(cols_equal),
            "completion_equal": bool(iso_equal),
            "ok": bool(cols_equal and iso_equal)}


def scenario_micro(cache_dir: str, quick: bool) -> dict:
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.loadgen.arrivals import RateCurve, paced_schedule
    from routest_tpu.loadgen.engine import KeepAliveClient, run_open_loop
    from routest_tpu.loadgen.workload import MixedWorkload
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor
    from routest_tpu.serve.wirechannel import WireChannelClient

    out: dict = {"scenario": "micro"}
    window_s = 3.0 if quick else 8.0
    port = _free_port()
    chan_port = _free_port()
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        "ROUTEST_MESH": "0",
        "ETA_MODEL_PATH": MODEL,
        "RTPU_WIRE": "1",
        "RTPU_WIRE_PORT": str(chan_port),
        "RTPU_COMPILE_CACHE": os.path.join(cache_dir, "xla"),
    })
    os.environ["RTPU_WIRE"] = "1"
    os.environ["RTPU_WIRE_PORT"] = str(chan_port)
    sup = ReplicaSupervisor([port], env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    gw = None
    try:
        if not sup.ready(timeout=600):
            raise RuntimeError("worker never became ready")
        frames0 = _family_total(
            "rtpu_wire_frames_total",
            lambda key: "sent" in key)
        gw = Gateway([("127.0.0.1", port)], FleetConfig(hedge=False),
                     supervisor=sup)
        httpd = gw.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"

        # (1) exact parity through the gateway — hard gate.
        out["parity"] = _parity_check(base)

        # (2) small-batch throughput, wire vs JSON, same seeded rows.
        thr: dict = {}
        for rows in (8, 64):
            per_mode = {}
            for mode in ("json", "binary"):
                wl = MixedWorkload(mix={"predict_eta_batch": 1.0},
                                   seed=11, batch_rows=rows,
                                   wire_format=mode)
                reqs = wl.sequence(64)
                n_ok, n_err, elapsed = _closed_loop(
                    base, reqs, window_s)
                per_mode[mode] = {
                    "ok": n_ok, "err": n_err,
                    "req_per_s": round(n_ok / elapsed, 1),
                    "rows_per_s": round(n_ok * rows / elapsed, 1)}
            ratio = (per_mode["binary"]["rows_per_s"]
                     / max(per_mode["json"]["rows_per_s"], 1e-9))
            thr[str(rows)] = {**per_mode,
                              "speedup": round(ratio, 2)}
        out["throughput"] = thr
        speedup_small = min(thr[k]["speedup"] for k in thr)
        out["speedup_small_batches"] = round(speedup_small, 2)

        # (3) gateway-added overhead: via-gateway wire p95 minus
        # direct-channel p95 on the same 64-row frame.
        wl = MixedWorkload(mix={"predict_eta_batch": 1.0}, seed=13,
                           batch_rows=64, wire_format="binary")
        frame = wl.sequence(1)[0].body
        n = 150 if quick else 400
        from routest_tpu.loadgen.workload import PlannedRequest

        preq = PlannedRequest(method="POST",
                              path="/api/predict_eta_batch",
                              body=frame, route="predict_eta_batch",
                              content_type=WIRE_CT)
        direct = WireChannelClient("127.0.0.1", chan_port)
        gw_client = KeepAliveClient(base, timeout=30.0)

        def one_direct() -> float:
            t0 = time.perf_counter()
            status, _body = direct.request("/api/predict_eta_batch",
                                           frame, timeout=30.0)
            assert status == 200
            return time.perf_counter() - t0

        def one_gw() -> float:
            t0 = time.perf_counter()
            status, _body = gw_client.send(preq)
            assert status == 200
            return time.perf_counter() - t0

        # Interleaved sampling: host drift (GC, scheduler) lands on
        # both legs equally instead of biasing whichever ran second.
        for _ in range(30):   # steady-state both paths first
            one_direct(), one_gw()
        lat_direct, lat_gw = [], []
        for _ in range(n):
            lat_direct.append(one_direct())
            lat_gw.append(one_gw())
        direct.close()
        gw_client.close()
        p95_direct = _p95_ms(lat_direct)
        p95_gw = _p95_ms(lat_gw)
        out["gateway_overhead"] = {
            "p95_direct_ms": round(p95_direct, 3),
            "p95_via_gateway_ms": round(p95_gw, 3),
            "added_p95_ms": round(p95_gw - p95_direct, 3),
            "budget_ms": GW_OVERHEAD_P95_MS,
            "samples": n}

        # (4) sustained rows/s through ONE gateway: open-loop
        # 1024-row binary frames (CO-correct pacing).
        rows = 1024
        rate = 130.0
        duration = 6.0 if quick else 15.0
        wl = MixedWorkload(mix={"predict_eta_batch": 1.0}, seed=17,
                           batch_rows=rows, wire_format="binary")
        offsets = paced_schedule(RateCurve.constant(rate), duration)
        reqs = wl.sequence(min(len(offsets), 64))
        reqs = [reqs[i % len(reqs)] for i in range(len(offsets))]
        records = run_open_loop([base], offsets, reqs, workers=16,
                                timeout=60.0)
        ok = [r for r in records if r.status == 200]
        span = max((r.offset_s + r.latency_s for r in ok),
                   default=duration)
        sustained = len(ok) * rows / max(span, 1e-9)
        out["sustained"] = {
            "rows_per_frame": rows,
            "offered_rps": rate,
            "duration_s": duration,
            "ok": len(ok), "errors": len(records) - len(ok),
            "p95_ms": round(_p95_ms([r.latency_s for r in ok]), 2),
            "rows_per_s": round(sustained, 0),
            "floor_rows_per_s": SUSTAINED_ROWS_PER_S}

        # (5) the channel carried it: frames sent over the persistent
        # channel, and connection reuse ≈ total (not one conn per req).
        frames = _family_total("rtpu_wire_frames_total",
                               lambda key: "sent" in key) - frames0
        reused = _family_total("rtpu_wire_conns_total",
                               lambda key: "reused" in key)
        fresh = _family_total("rtpu_wire_conns_total",
                              lambda key: "fresh" in key)
        out["channel"] = {
            "frames_sent": int(frames),
            "conns_reused": int(reused),
            "conns_fresh": int(fresh),
            "reuse_ratio": round(reused / max(reused + fresh, 1), 4)}

        checks = {
            "parity_exact": out["parity"]["ok"],
            "speedup_small_batches_ge_2x":
                speedup_small >= SPEEDUP_MIN,
            "gateway_overhead_p95_lt_1ms":
                (p95_gw - p95_direct) < GW_OVERHEAD_P95_MS,
            "sustained_ge_100k_rows_per_s":
                sustained >= SUSTAINED_ROWS_PER_S,
            "channel_carried_traffic": frames > 0,
            "connections_reused": out["channel"]["reuse_ratio"] > 0.9,
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        os.environ.pop("RTPU_WIRE_PORT", None)
        if gw is not None:
            gw.drain(timeout=5)
        sup.drain(timeout=15)
    return out


# ── probe parity across flip + swap ──────────────────────────────────


def scenario_probe_parity(bp, extract: str, cache_dir: str,
                          quick: bool) -> dict:
    import jax  # noqa: F401  (forces backend init before the fleet)

    from routest_tpu.loadgen.arrivals import RateCurve, paced_schedule
    from routest_tpu.loadgen.engine import run_open_loop
    from routest_tpu.loadgen.workload import MixedWorkload
    from routest_tpu.train.checkpoint import load_model, save_model

    out: dict = {"scenario": "probe_parity"}
    os.environ["RTPU_WIRE"] = "1"
    work = tempfile.mkdtemp(prefix="wire-probe-")
    fleet = bp.Fleet(live=True, extract=extract, cache_dir=cache_dir,
                     work_dir=work, probe_interval=1.0)
    try:
        prober = fleet.arm_prober()
        out["wire_kind_armed"] = "wire" in prober.kinds

        # Open-loop binary load for the whole transition window.
        stop = threading.Event()
        duration = 90.0 if quick else 180.0
        wl = MixedWorkload(mix={"predict_eta_batch": 1.0}, seed=23,
                           batch_rows=64, wire_format="binary")
        offsets = paced_schedule(RateCurve.constant(4.0), duration)
        base_reqs = wl.sequence(64)
        reqs = [base_reqs[i % len(base_reqs)]
                for i in range(len(offsets))]
        records: list = []

        def load_thread() -> None:
            records.extend(run_open_loop(
                [fleet.base], offsets, reqs, workers=4, timeout=60.0,
                stop=stop))

        loader = threading.Thread(target=load_thread)
        loader.start()

        # A verified model swap: within-gate perturbation, both
        # replicas' reload watchers land it through the golden gate.
        import jax as _jax

        model, params = load_model(fleet.model_path)
        close = _jax.tree_util.tree_map(lambda x: x * (1.0 + 1e-4),
                                        params)
        save_model(fleet.model_path, model, close)
        st = os.stat(fleet.model_path)
        os.utime(fleet.model_path,
                 ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))

        def swaps_accepted() -> int:
            total = 0
            for p in fleet.ports:
                reg = bp._fetch(f"http://127.0.0.1:{p}/api/metrics",
                                timeout=30).get("registry", {})
                for s in reg.get("rtpu_model_swaps_total",
                                 {}).get("series", ()):
                    if s.get("labels", {}).get("result") == "accepted":
                        total += int(s.get("value", 0))
            return total

        # ≥1 legitimate metric flip: probe drivers stream real
        # observations, the live pipeline customizes a new epoch.
        epoch0 = max(bp._fetch(f"http://127.0.0.1:{p}/api/live",
                               timeout=30).get("epoch", 0)
                     for p in fleet.ports)
        fleet.start_probe_drivers()
        deadline = time.time() + (90 if quick else 150)
        swaps = flips = 0
        while time.time() < deadline and (swaps < 1 or flips < 1):
            swaps = swaps_accepted()
            flips = max(bp._fetch(f"http://127.0.0.1:{p}/api/live",
                                  timeout=30).get("epoch", 0)
                        for p in fleet.ports) - epoch0
            time.sleep(1.0)
        time.sleep(6 * fleet.prober_cfg.interval_s)  # post-flip rounds
        stop.set()
        loader.join(timeout=60)
        out["swaps_accepted"] = swaps
        out["metric_flips"] = flips

        snap = fleet.prober.snapshot()
        wire_state = snap["probes"].get("wire", {})
        slo = fleet.prober.slo.snapshot()["objectives"]
        ok_load = [r for r in records if r.status == 200]
        out["wire_verdict"] = wire_state.get("verdict")
        out["correctness_wire_state"] = \
            slo.get("correctness:wire", {}).get("state")
        out["probe_rounds"] = fleet.prober._rounds
        out["load"] = {"ok": len(ok_load),
                       "errors": len(records) - len(ok_load),
                       "p95_ms": round(_p95_ms(
                           [r.latency_s for r in ok_load]), 2)}
        checks = {
            "wire_kind_armed": out["wire_kind_armed"],
            "verified_swap_ge_1": swaps >= 1,
            "metric_flip_ge_1": flips >= 1,
            "wire_probe_green": wire_state.get("verdict") == "pass",
            "correctness_wire_never_paged":
                out["correctness_wire_state"] == "ok",
            "binary_load_served": len(ok_load) > 0
                and len(ok_load) >= 0.9 * max(len(records), 1),
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


# ── main ─────────────────────────────────────────────────────────────


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="shorter windows + smaller extract (CI)")
    parser.add_argument("--nodes", type=int, default=6000)
    parser.add_argument("--cache-dir", default=os.path.join(
        REPO, "artifacts", "bench_cache", "wire"))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "wire.json"))
    parser.add_argument("--scenario", default=None,
                        help="run one scenario (debug)")
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 4000)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(args.cache_dir, exist_ok=True)
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache(os.path.join(args.cache_dir, "xla"))

    t0 = time.time()
    scenarios: dict = {}
    plan = [("micro",
             lambda: scenario_micro(args.cache_dir, args.quick))]
    if args.scenario in (None, "probe_parity"):
        bp = _bench_probing()
        print("[1/3] extract + overlay cache "
              f"({args.nodes:,} nodes)…", flush=True)
        extract = bp.build_extract(args.nodes, args.cache_dir)
        plan.append(("probe_parity", lambda: scenario_probe_parity(
            bp, extract, args.cache_dir, args.quick)))
    for i, (name, run) in enumerate(plan):
        if args.scenario and name != args.scenario:
            continue
        print(f"[{i + 2}/3] scenario {name}…", flush=True)
        t = time.perf_counter()
        try:
            scenarios[name] = run()
        except Exception as e:
            import traceback

            traceback.print_exc()
            scenarios[name] = {"scenario": name, "pass": False,
                               "error": f"{type(e).__name__}: {e}"}
        scenarios[name]["wall_s"] = round(time.perf_counter() - t, 1)
        print(f"  {name}: "
              f"{'PASS' if scenarios[name].get('pass') else 'FAIL'} "
              f"({scenarios[name]['wall_s']}s)", flush=True)

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    backend = jax.devices()[0].platform
    record = {
        "generated_unix": int(t0),
        "host": {"cpus": n_cpus, "platform": sys.platform,
                 "backend": backend},
        "host_caveat": (
            f"cpu-backend record on {n_cpus} core(s): absolute rows/s "
            "and p95s are time-shared-host numbers; judge the "
            "structural checks (bitwise parity, speedup ratio, "
            "overhead delta, probe green across flip+swap), not "
            "wall-ms" if backend != "tpu" else None),
        "skipped": ("tpu wire: CPU fallback rows — re-record when a "
                    "tunnel appears (scripts/run_tpu_battery.sh does "
                    "it automatically)" if backend != "tpu" else None),
        "config": {
            "nodes": args.nodes,
            "speedup_min": SPEEDUP_MIN,
            "gateway_overhead_p95_ms": GW_OVERHEAD_P95_MS,
            "sustained_floor_rows_per_s": SUSTAINED_ROWS_PER_S,
            "cache_dir": args.cache_dir,
            "quick": bool(args.quick),
        },
        "scenarios": scenarios,
    }
    if args.scenario:
        record["partial"] = f"--scenario {args.scenario} (debug run)"
    record["checks"] = {name: bool(s.get("pass"))
                        for name, s in scenarios.items()}
    record["all_pass"] = (bool(record["checks"])
                          and all(record["checks"].values())
                          and (args.scenario is not None
                               or len(scenarios) == 2))
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=_jsonable)
        f.write("\n")
    print(f"wrote {args.out} "
          f"(all_pass={record['all_pass']}, "
          f"{round(time.time() - t0, 1)}s)", flush=True)
    sys.exit(0 if record["all_pass"] else 1)


if __name__ == "__main__":
    main()
