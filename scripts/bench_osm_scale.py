"""Metro-scale OSM ingest + routing curve → artifacts/osm_scale.json.

The OSM path (``data/osm.py`` → ``RoadRouter``) was proven on an
18-node fixture; this script proves it at city scale without shipping a
licensed extract: per size it generates a metro street network with OSM
topology (degree-2 bend chains + one-ways via ``subdivide_graph``),
WRITES it as OSM XML (``save_osm``), ingests it back through the exact
parser a real extract would use, and routes over it. Per row it
records:

- parse + router-build time, with the overlay build broken down per
  level (partition / contraction / per-level precompute),
- cold and warm 16-waypoint solves, plus the warm solve's PER-PHASE
  breakdown (``HierarchicalIndex.timed_query``: in-cell phase 1,
  per-level ascends, top overlay BF, per-level descend stitches,
  chain expansion) so a future regression localizes to a phase
  instead of a single opaque ``solve_warm_ms``,
- the full matrix operation (solve + M×M distances AND durations —
  the ORS-comparable call), and
- oracle parity vs a float64 scipy Dijkstra (disagreement in EITHER
  direction on reachability is a failure).

Usage: python scripts/bench_osm_scale.py [--sizes 50000 100000 250000]
       [--quick] [--cpu] [--no-verify] [--out artifacts/osm_scale.json]
(…then ``python scripts/train_gnn.py --osm <written path>`` trains the
learned leg costs on the same extract.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _verify(router, nodes, dist, np):
    """Max relative error vs a float64 Dijkstra oracle (scipy)."""
    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra

    n = router.n_nodes
    adj = sp.coo_matrix(
        (router.length_m, (router.senders, router.receivers)),
        shape=(n, n)).tocsr()
    want = dijkstra(adj, directed=True, indices=np.asarray(nodes, np.int64))
    finite = np.isfinite(want)
    if (dist[finite] > 1e37).any() or (dist[~finite] < 1e37).any():
        return float("inf")
    err = np.abs(dist[finite] - want[finite]) / np.maximum(want[finite], 1.0)
    return float(err.max())


def bench_size(n_nodes: int, waypoints: int, verify: bool, np, rng) -> dict:
    from routest_tpu.data.osm import load_osm, save_osm
    from routest_tpu.data.road_graph import generate_road_graph, subdivide_graph
    from routest_tpu.optimize.road_router import RoadRouter

    # intersections + 2 bends/street ≈ 5.86 nodes per intersection for
    # the k=4 kNN street graph (same constant as bench_router_scale).
    n_int = max(1024, int(n_nodes / 5.86))
    t0 = time.perf_counter()
    base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
    streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1, seed=0)
    gen_s = time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "metro.osm.gz")
        t0 = time.perf_counter()
        save_osm(path, streets)
        write_s = time.perf_counter() - t0
        size_mb = os.path.getsize(path) / 1e6
        t0 = time.perf_counter()
        extract = load_osm(path)
        parse_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    router = RoadRouter(graph=extract, use_gnn=False, use_transformer=False)
    build_s = time.perf_counter() - t0

    pts = np.stack([
        rng.uniform(14.40, 14.68, waypoints),
        rng.uniform(120.96, 121.10, waypoints),
    ], axis=1).astype(np.float32)
    nodes = router.snap(pts)

    t0 = time.perf_counter()
    dist, _ = router.shortest(nodes)
    cold_ms = 1000 * (time.perf_counter() - t0)
    warm = []
    for _ in range(3):
        t0 = time.perf_counter()
        dist, _ = router.shortest(nodes)
        warm.append(1000 * (time.perf_counter() - t0))
    warm_ms = min(warm)

    # Per-phase breakdown of the warm query (own dispatches; the fused
    # serving program is what cold/warm above measure).
    phases = {}
    if router._hier is not None:
        router._hier.timed_query(np.asarray(nodes, np.int32))  # warm jits
        _, phases = router._hier.timed_query(np.asarray(nodes, np.int32))

    # Full matrix op: solve + M×M distance and duration matrices,
    # exactly as /api/matrix serves them (min-of-3, fresh RoadLegs).
    matrix_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        legs = router.route_legs(pts, 1.0, hour=8)
        legs.duration_matrix()
        matrix_times.append(time.perf_counter() - t0)

    row = {
        "nodes": int(router.n_nodes),
        "edges": int(len(router.senders)),
        "waypoints": waypoints,
        "extract_mb": round(size_mb, 2),
        "generate_s": round(gen_s, 2),
        "write_s": round(write_s, 2),
        "parse_s": round(parse_s, 2),
        "router_build_s": round(build_s, 2),
        "solve_cold_ms": round(cold_ms, 1),
        "solve_warm_ms": round(warm_ms, 1),
        "matrix_warm_ms": round(1000 * min(matrix_times), 1),
        "reachable_frac": round(float((dist < 1e37).mean()), 4),
        "query_phases_ms": phases,
        **router.solver_info,
    }
    if verify:
        row["oracle_max_rel_err"] = _verify(router, nodes, dist, np)
    return row


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=int, nargs="+",
                        default=[50_000, 100_000, 250_000])
    parser.add_argument("--quick", action="store_true",
                        help="small curve for the slow-marked test "
                             "(20k/50k, still multi-level at the top)")
    parser.add_argument("--waypoints", type=int, default=16)
    parser.add_argument("--no-verify", action="store_true",
                        help="skip the scipy Dijkstra oracle per row")
    parser.add_argument("--cpu", action="store_true")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    # This bench measures the SOLVER: repeated identical route_legs
    # calls would otherwise hit the route fastlane and time the cache
    # (bench_router_serving.py is where the cache is measured).
    os.environ.setdefault("ROUTEST_ROUTE_CACHE", "0")
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import numpy as np

    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache()
    sizes = [20_000, 50_000] if args.quick else args.sizes
    rng = np.random.default_rng(7)
    rows = []
    for n in sizes:
        print(f"[{n:,} nodes] generating + ingesting…", flush=True)
        row = bench_size(n, args.waypoints, not args.no_verify, np, rng)
        rows.append(row)
        print(f"  {row['nodes']:>9,} nodes {row['edges']:>9,} edges | "
              f"build {row['router_build_s']}s | cold "
              f"{row['solve_cold_ms']}ms warm {row['solve_warm_ms']}ms "
              f"matrix {row['matrix_warm_ms']}ms"
              + (f" | oracle {row.get('oracle_max_rel_err'):.2e}"
                 if "oracle_max_rel_err" in row else ""), flush=True)
        if row.get("query_phases_ms"):
            print(f"  phases: {json.dumps(row['query_phases_ms'])}",
                  flush=True)

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    report = {
        "backend": jax.default_backend(),
        "host": {
            "cpus": n_cpus,
            "note": "wall times scale with host cores; the per-phase "
                    "breakdown is the portable signal",
        },
        # Structural, not prose: bench.py's TPU probes have CPU-fallen-
        # back for 3 straight battery rounds, so every artifact must
        # carry a machine-readable caveat a dashboard can filter on
        # (ROADMAP housekeeping).
        "host_caveat": (None if jax.default_backend() == "tpu" else
                        f"cpu-backend record on {n_cpus} core(s): compare "
                        f"phase ratios and oracle parity, not wall ms"),
        "waypoints": args.waypoints,
        "rows": rows,
    }
    out = args.out or os.path.join(REPO, "artifacts", "osm_scale.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)

    print(f"\n| nodes | edges | solver | levels | warm solve | matrix | "
          f"oracle err |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        ov = r.get("overlay", {})
        err = r.get("oracle_max_rel_err")
        print(f"| {r['nodes']:,} | {r['edges']:,} | {r['solver']} | "
              f"{ov.get('n_levels', '-')} | {r['solve_warm_ms']} ms | "
              f"{r['matrix_warm_ms']} ms | "
              f"{(f'{err:.1e}' if err is not None else '-')} |")
    print(f"\nbackend={report['backend']} cpus={n_cpus} → {out}")
    bad = [r for r in rows
           if r.get("oracle_max_rel_err", 0.0) > 1e-5
           or r["reachable_frac"] < 0.99]
    sys.exit(1 if bad else 0)


if __name__ == "__main__":
    main()
