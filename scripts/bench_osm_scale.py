"""Metro-scale OSM ingest + routing benchmark → artifacts/osm_scale.json.

The OSM path (``data/osm.py`` → ``RoadRouter``) was proven on an
18-node fixture; this script proves it at city scale without shipping a
licensed extract: generate a metro-sized street network, WRITE it as
OSM XML (``save_osm``), then ingest it back through the exact parser a
real extract would use and route over it. Reported: parse time, router
build time, cold/warm 16-waypoint solve — the numbers that decide
whether a deploy can point ``ROAD_GRAPH_OSM`` at a city.

Usage: python scripts/bench_osm_scale.py [--nodes 8192] [--cpu]
(…then ``python scripts/train_gnn.py --osm <written path>`` trains the
learned leg costs on the same extract.)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=8192)
    parser.add_argument("--waypoints", type=int, default=16)
    parser.add_argument("--keep", metavar="PATH", default=None,
                        help="also write the generated extract here "
                             "(e.g. to feed train_gnn --osm)")
    parser.add_argument("--cpu", action="store_true")
    args = parser.parse_args()
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import numpy as np

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.data.osm import load_osm, save_osm
    from routest_tpu.data.road_graph import generate_road_graph
    from routest_tpu.optimize.road_router import RoadRouter

    enable_compile_cache()
    backend = jax.default_backend()
    print(f"[1/4] generating {args.nodes}-node street network…")
    graph = generate_road_graph(n_nodes=args.nodes, seed=0)

    path = args.keep or os.path.join(tempfile.gettempdir(),
                                     f"metro_{args.nodes}.osm.gz")
    t0 = time.time()
    save_osm(path, graph)
    write_s = time.time() - t0
    size_mb = os.path.getsize(path) / 1e6
    print(f"      extract → {path} ({size_mb:.1f} MB, {write_s:.1f}s)")

    print("[2/4] ingesting through the OSM parser…")
    t0 = time.time()
    loaded = load_osm(path)
    parse_s = time.time() - t0
    n_edges = len(loaded["senders"])
    print(f"      {len(loaded['node_coords'])} nodes / {n_edges} edges "
          f"in {parse_s:.1f}s")

    print("[3/4] building router (bridging + device upload)…")
    t0 = time.time()
    router = RoadRouter(graph=loaded, use_gnn=False)
    build_s = time.time() - t0

    print(f"[4/4] {args.waypoints}-waypoint solves on {backend}…")
    rng = np.random.default_rng(0)
    lat = rng.uniform(14.40, 14.80, args.waypoints)
    lon = rng.uniform(120.90, 121.15, args.waypoints)
    pts = np.stack([lat, lon], axis=1).astype(np.float32)
    t0 = time.time()
    legs = router.route_legs(pts)
    cold_ms = (time.time() - t0) * 1000
    t0 = time.time()
    legs = router.route_legs(pts + 1e-3)
    warm_ms = (time.time() - t0) * 1000
    finite = float(np.isfinite(legs.dist_m).mean())
    print(f"      cold {cold_ms:.0f} ms, warm {warm_ms:.0f} ms, "
          f"matrix finite {finite:.2f}")

    report = {
        "backend": backend,
        "extract": (args.keep if args.keep else "regenerate via --keep"),
        "generator": f"routest_tpu.data.road_graph.generate_road_graph("
                     f"n_nodes={args.nodes}, seed=0) via this script",
        "nodes": int(router.n_nodes),
        "edges": int(len(router.senders)),
        "extract_mb": round(size_mb, 2),
        "write_s": round(write_s, 2),
        "parse_s": round(parse_s, 2),
        "router_build_s": round(build_s, 2),
        "waypoints": args.waypoints,
        "solve_cold_ms": round(cold_ms, 1),
        "solve_warm_ms": round(warm_ms, 1),
        "matrix_finite_frac": finite,
    }
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = os.path.join(repo, "artifacts", "osm_scale.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"      report → {out}")
    sys.exit(0 if finite == 1.0 else 1)


if __name__ == "__main__":
    main()
