"""Telemetry end-to-end: an injected latency regression, seen three ways.

The ISSUE-13 acceptance scenario: under open-loop load against a real
2-replica fleet, a latency regression is "deployed" (a rolling restart
onto a version whose env overlay carries seeded ``device.compute``
latency chaos — the dominant real incident shape: a bad deploy), and
the telemetry layer must catch it end to end:

(a) **timeline** — the regression is visible in the gateway FLEET
    timeline (the scraped per-replica frames merged per slot) in the
    first complete window after injection: merged p95 over the
    regression factor vs the pre-injection baseline;
(b) **tail sampling** — the replica span buffers hold ≥1 tail-KEPT
    trace of an actually-slow request (root over its route's SLO
    threshold) carrying the provenance attrs (``fastlane.predict``
    with model generation + metric epoch + cache outcome) — the trace
    head sampling would have found only by luck;
(c) **bundles** — an anomaly- or page-triggered flight-recorder bundle
    embeds a non-empty ``timeline.json`` slice covering the injection
    instant — the postmortem answers *when did it start*;
(d) **budget** — the committed ``artifacts/obs_overhead.json`` shows
    the always-on posture within the ≤5% p95 budget vs obs-off.

Also recorded (and gated): the per-VERSION timeline view separates the
regressed version from the baseline, and the SLO warn/page edge armed
a triggered profile capture on the replica.

Writes ``artifacts/telemetry.json``.

Usage: python scripts/bench_telemetry.py [--quick]
       [--out artifacts/telemetry.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")

STEP_S = 1.0          # finest timeline resolution for the scenario
SLOW_MS = 250.0       # per-route SLO latency threshold (env-set below)
CHAOS_MS = 400       # injected device latency (≫ SLOW_MS)
REGRESSION_FACTOR = 2.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get_json(base, path, timeout=15.0):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return json.loads(r.read())
    except (urllib.error.URLError, OSError, ValueError):
        return {}


def boot_fleet(recorder_dir: str):
    """Two real serving workers behind an in-process gateway, armed
    with the full ISSUE-13 posture: 1 s timeline frames, tail-based
    trace retention, tight predict latency SLO, watcher + profiler on."""
    from routest_tpu.core.config import FleetConfig, RecorderConfig
    from routest_tpu.obs.recorder import FlightRecorder, configure_recorder
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    telemetry_env = {
        "RTPU_TIMELINE_RES": "1x600,10x360",
        "RTPU_TAIL_SAMPLE": "1",
        "RTPU_SLO_OBJECTIVES":
            f"/api/predict_eta:availability=0.999,latency_ms={SLOW_MS:g},"
            "latency_target=0.95",
        "RTPU_RECORDER_MIN_INTERVAL_S": "0",
    }
    # The in-process gateway reads os.environ (tracer, timeline).
    os.environ.update(telemetry_env)
    configure_recorder(FlightRecorder(RecorderConfig(
        dir=os.path.join(recorder_dir, "gateway"), min_interval_s=0.0)))
    ports = [_free_port(), _free_port()]
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        "ROUTEST_WARM_BUCKETS": "0",
        "ROUTEST_MESH": "0",
        "ETA_MODEL_PATH": MODEL,
        "RTPU_RECORDER_DIR": os.path.join(recorder_dir, "workers"),
        **telemetry_env,
    })
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    if not sup.ready(timeout=300):
        sup.drain(timeout=10)
        raise RuntimeError("fleet workers never became ready")
    cfg = FleetConfig(eject_after=5, cooldown_s=1.0, max_inflight=64,
                      queue_depth=256, hedge=False)
    gw = Gateway([("127.0.0.1", p) for p in ports], cfg, supervisor=sup,
                 version="v1-baseline")
    httpd = gw.serve("127.0.0.1", 0)
    return sup, gw, f"http://127.0.0.1:{httpd.server_address[1]}"


def start_load(base: str, rate: float, duration_s: float,
               stop: threading.Event):
    """Open-loop paced predict_eta load (coordinated-omission-correct:
    the generator never slows down because the fleet did). Every body
    is unique so each request does real device work — a cached answer
    cannot mask a device-latency regression."""
    from routest_tpu.loadgen.arrivals import RateCurve, paced_schedule
    from routest_tpu.loadgen.engine import run_open_loop
    from routest_tpu.loadgen.workload import PlannedRequest

    offsets = paced_schedule(RateCurve.constant(rate), duration_s)
    requests = [PlannedRequest(
        method="POST", path="/api/predict_eta",
        body={"summary": {"distance": 8000 + i}, "weather": "Sunny",
              "traffic": "Medium", "driver_age": 35,
              "pickup_time": "2026-08-05T18:00:00"},
        route="predict_eta") for i in range(len(offsets))]
    records: list = []
    thread = threading.Thread(
        target=lambda: records.extend(run_open_loop(
            [base], offsets, requests, workers=24, timeout=30.0,
            stop=stop)),
        daemon=True)
    thread.start()
    return thread, records


def inject_regression(sup, gw, boot_timeout_s: float):
    """Roll the fleet onto the regressed version: each replica is
    replaced (drain → spawn → boot watch → health gate → join) with an
    env overlay carrying seeded device-latency chaos. Returns the unix
    instant the FIRST regressed replica joined (= regression onset)."""
    from routest_tpu.serve.fleet.rollout import replace_replica

    overlay = {"RTPU_CHAOS_SPEC":
               f"device.compute:latency=1.0/{CHAOS_MS}",
               "RTPU_CHAOS_SEED": "3"}
    with gw._lock:
        rids = sorted((r.id for r in gw.replicas if not r.draining),
                      key=lambda rid: int(rid[1:]))
    t_first = None
    for rid in rids:
        result = replace_replica(
            sup, gw, rid, version="v2-regressed", env=overlay,
            boot_timeout_s=boot_timeout_s, health_timeout_s=30.0)
        if not result.get("ok"):
            raise RuntimeError(f"injection rollout failed: {result}")
        if t_first is None:
            t_first = time.time()
    return t_first


def _hist_p95(frame, family="request_duration_seconds"):
    fam = (frame.get("families") or {}).get(family)
    if not fam:
        return None, 0
    le = fam.get("le") or ()
    buckets = None
    count = 0
    for row in fam["series"]:
        count += row.get("count", 0)
        b = row.get("buckets")
        if b is not None:
            buckets = (list(b) if buckets is None
                       else [x + y for x, y in zip(buckets, b)])
    if not buckets or not le:
        return None, count
    from routest_tpu.obs.timeline import bucket_quantile

    return bucket_quantile(le, buckets, 0.95), count


def check_fleet_timeline(base: str, t_inject: float, timeout_s: float,
                         baseline_p95: float) -> dict:
    """(a): poll the gateway fleet timeline for the first complete
    post-injection window and judge its merged p95."""
    deadline = time.monotonic() + timeout_s
    out = {"baseline_p95_s": round(baseline_p95, 4)}
    while time.monotonic() < deadline:
        doc = _get_json(base, f"/api/timeline?scope=fleet&step={STEP_S:g}"
                              "&family=request_duration_seconds")
        frames = [f for f in (doc.get("frames") or [])
                  if f["t"] - f["dur"] >= t_inject]
        for frame in frames:
            p95, count = _hist_p95(frame)
            if p95 is None or count < 3:
                continue
            out.update({
                "frame_t": frame["t"],
                "frame_count": count,
                "p95_s": round(p95, 4),
                "windows_after_inject": round(
                    (frame["t"] - t_inject) / STEP_S, 2),
                "regression_visible": bool(
                    p95 >= REGRESSION_FACTOR * baseline_p95
                    and p95 >= SLOW_MS / 1000.0),
            })
            if out["regression_visible"]:
                return out
        time.sleep(STEP_S / 2)
    out.setdefault("regression_visible", False)
    return out


def baseline_fleet_p95(base: str) -> float:
    doc = _get_json(base, f"/api/timeline?scope=fleet&step={STEP_S:g}"
                          "&family=request_duration_seconds")
    best, weight = 0.0, 0
    for frame in doc.get("frames") or []:
        p95, count = _hist_p95(frame)
        if p95 is not None and count >= 3 and count > weight:
            best, weight = p95, count
    return best or 0.02


def check_tail_traces(sup) -> dict:
    """(b): the replicas' span buffers hold tail-kept SLOW traces of
    actually-slow requests with provenance attrs."""
    found = {"tail_slow_roots": 0, "with_provenance": 0, "example": None}
    for port in sup.ports:
        doc = _get_json(f"http://127.0.0.1:{port}", "/api/trace")
        spans = doc.get("spans") or []
        by_trace: dict = {}
        for s in spans:
            by_trace.setdefault(s.get("trace_id"), []).append(s)
        for s in spans:
            # The replica's tail-kept root sits BEHIND the gateway, so
            # its parent_id points at the gateway's forward span —
            # local roots are parentless OR remote-parented.
            local_root = s.get("parent_id") is None \
                or s.get("remote_parent")
            if not local_root or s.get("tail") != "slow":
                continue
            if s.get("duration_ms", 0) < SLOW_MS:
                continue
            found["tail_slow_roots"] += 1
            tree = by_trace.get(s.get("trace_id"), [])
            prov = next((c for c in tree
                         if c.get("name") == "fastlane.predict"
                         and "model_generation" in (c.get("attrs") or {})),
                        None)
            if prov is not None:
                found["with_provenance"] += 1
                if found["example"] is None:
                    found["example"] = {
                        "trace_id": s["trace_id"],
                        "duration_ms": s["duration_ms"],
                        "threshold_ms": SLOW_MS,
                        "provenance": prov["attrs"],
                    }
    found["ok"] = found["with_provenance"] >= 1
    return found


def check_bundles(recorder_dir: str, t_inject: float,
                  timeout_s: float = 45.0) -> dict:
    """(c): an anomaly/page bundle embeds a timeline slice covering the
    injection instant; also report the triggered-profile bundle."""
    dirs = [os.path.join(recorder_dir, "workers"),
            os.path.join(recorder_dir, "gateway")]
    deadline = time.monotonic() + timeout_s
    out = {"bundles": [], "incident_bundle": None, "profile_bundle": None}
    while time.monotonic() < deadline:
        out["bundles"] = []
        for root in dirs:
            if not os.path.isdir(root):
                continue
            for name in sorted(os.listdir(root)):
                if not name.startswith("pm_"):
                    continue
                bundle = os.path.join(root, name)
                try:
                    manifest = json.load(
                        open(os.path.join(bundle, "manifest.json")))
                except (OSError, ValueError):
                    continue
                reason = str(manifest.get("reason", ""))
                entry = {"reason": reason, "name": name}
                out["bundles"].append(entry)
                if reason.startswith("profile_") \
                        and out["profile_bundle"] is None:
                    folded = os.path.join(bundle, "profile.folded")
                    if os.path.exists(folded) \
                            and os.path.getsize(folded) > 0:
                        out["profile_bundle"] = entry
                if not (reason.startswith("anomaly_")
                        or reason.startswith("slo_page")):
                    continue
                try:
                    doc = json.load(
                        open(os.path.join(bundle, "timeline.json")))
                except (OSError, ValueError):
                    continue
                frames = [f for comp in doc.values()
                          for f in comp.get("frames", [])]
                covers = any(f["t"] >= t_inject for f in frames)
                if frames and covers and out["incident_bundle"] is None:
                    out["incident_bundle"] = {
                        **entry, "timeline_frames": len(frames),
                        "covers_incident": covers}
        if out["incident_bundle"] and out["profile_bundle"]:
            break
        time.sleep(1.0)
    out["ok"] = out["incident_bundle"] is not None
    out["profile_ok"] = out["profile_bundle"] is not None
    return out


def check_version_view(base: str, t_inject: float) -> dict:
    """The per-version tentpole view: the regressed version's merged
    p95 must sit above the baseline version's."""
    doc = _get_json(base, "/api/timeline?scope=versions"
                          "&family=request_duration_seconds")
    versions = doc.get("versions") or {}
    out = {"versions_seen": sorted(versions)}
    p95s = {}
    for label, payload in versions.items():
        best, weight = None, 0
        for frame in payload.get("frames") or []:
            p95, count = _hist_p95(frame)
            if p95 is not None and count > weight:
                best, weight = p95, count
        if best is not None:
            p95s[label] = round(best, 4)
    out["p95_by_version"] = p95s
    base_p95 = p95s.get("v1-baseline")
    reg_p95 = p95s.get("v2-regressed")
    out["ok"] = bool(base_p95 is not None and reg_p95 is not None
                     and reg_p95 >= REGRESSION_FACTOR * base_p95)
    return out


def wait_for_page(base: str, timeout_s: float) -> dict:
    """Poll /api/slo?replicas=1 until a latency objective pages."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        snap = _get_json(base, "/api/slo?replicas=1", timeout=10.0)
        candidates = [("gateway", snap)]
        for rid, rep in (snap.get("replica_slo") or {}).items():
            candidates.append((f"replica:{rid}", rep))
        for component, payload in candidates:
            for name, obj in (payload.get("objectives") or {}).items():
                if obj.get("state") == "page":
                    return {"paged": True, "objective": name,
                            "component": component,
                            "at_unix": round(time.time(), 2)}
        time.sleep(0.25)
    return {"paged": False}


def main() -> None:
    from routest_tpu.utils.logging import get_logger

    log = get_logger("routest_tpu.bench_telemetry")
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="shorter phases (CI re-verification)")
    parser.add_argument("--rate", type=float, default=8.0,
                        help="open-loop request rate (per second)")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "telemetry.json"))
    args = parser.parse_args()
    baseline_s = 10.0 if args.quick else 20.0
    regression_s = 60.0 if args.quick else 120.0
    boot_timeout_s = 240.0

    recorder_dir = tempfile.mkdtemp(prefix="telemetry-bench-")
    t0 = time.time()
    sup, gw, base = boot_fleet(recorder_dir)
    stop = threading.Event()
    record = {
        "generated_unix": int(t0),
        "host": {"cpu_count": os.cpu_count(), "platform": sys.platform},
        "scenario": {
            "replicas": 2, "rate_rps": args.rate,
            "baseline_s": baseline_s,
            "slow_threshold_ms": SLOW_MS,
            "injected_device_latency_ms": CHAOS_MS,
            "timeline_step_s": STEP_S,
            "injection": "rolling restart onto version v2-regressed "
                         "whose env overlay carries seeded "
                         "device.compute latency chaos (a bad deploy)",
        },
    }
    try:
        load_thread, _records = start_load(
            base, args.rate, baseline_s + regression_s + 300.0, stop)
        log.info("telemetry_baseline_phase", seconds=baseline_s)
        time.sleep(baseline_s)
        baseline_p95 = baseline_fleet_p95(base)
        log.info("telemetry_injecting", baseline_p95_s=baseline_p95)
        t_inject = inject_regression(sup, gw, boot_timeout_s)
        record["t_inject_unix"] = round(t_inject, 2)

        timeline = check_fleet_timeline(base, t_inject,
                                        timeout_s=regression_s,
                                        baseline_p95=baseline_p95)
        record["fleet_timeline"] = timeline
        log.info("telemetry_timeline_checked", **timeline)

        record["slo"] = wait_for_page(base, timeout_s=regression_s)
        record["tail_traces"] = check_tail_traces(sup)
        record["bundles"] = check_bundles(recorder_dir, t_inject)
        record["version_view"] = check_version_view(base, t_inject)
    finally:
        stop.set()
        try:
            load_thread.join(timeout=30)
        except Exception:
            pass
        from routest_tpu.obs.recorder import configure_recorder

        try:
            gw.drain(timeout=5)
        finally:
            sup.drain(timeout=15)
            configure_recorder(None)
            shutil.rmtree(recorder_dir, ignore_errors=True)

    # (d) the standing overhead budget, from the artifact of record.
    try:
        overhead = json.load(open(os.path.join(
            REPO, "artifacts", "obs_overhead.json")))
        record["obs_overhead"] = {
            "p95_overhead_always_on_pct":
                overhead.get("p95_overhead_always_on_pct"),
            "within_5pct_budget": overhead.get("within_5pct_budget"),
        }
    except (OSError, ValueError):
        record["obs_overhead"] = {"within_5pct_budget": None}

    record["checks"] = {
        "timeline_visible": record["fleet_timeline"].get(
            "regression_visible", False),
        "tail_trace_with_provenance": record["tail_traces"]["ok"],
        "bundle_covers_incident": record["bundles"]["ok"],
        "version_view_separates": record["version_view"]["ok"],
        "profile_captured": record["bundles"]["profile_ok"],
        "slo_paged": record["slo"]["paged"],
        "overhead_within_budget": bool(
            record["obs_overhead"]["within_5pct_budget"]),
    }
    record["all_pass"] = all(record["checks"].values())
    record["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    log.info("telemetry_written", path=args.out,
             all_pass=record["all_pass"], **record["checks"])
    print(json.dumps(record, indent=2))
    if not record["all_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
