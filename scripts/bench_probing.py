"""Blackbox probing end to end → artifacts/probing.json.

The ISSUE-15 acceptance scenario: a real fleet (supervisor + workers +
in-process gateway, live traffic where the scenario needs metric
epochs) under open-loop load, with the blackbox prober armed. Three
injected correctness faults — each invisible to every layer built
before this PR, because the replica keeps answering well-formed 200s —
must each be detected by the prober, page the correctness SLO within a
bounded window, and produce a flight-recorder bundle naming the
faulty replica and embedding the probe/oracle pair:

- ``compute_divergence`` — a replica rolled onto seeded
  ``device.compute:skew`` chaos (the silently-wrong device: outputs
  perturbed, status 200);
- ``stale_epoch``       — a replica whose ``live.customize`` cycles
  are chaos-dropped, so it serves a frozen metric epoch while the
  fleet moves on (the skew failure rollouts / multi-region create);
- ``divergent_model``   — a corrupt-ish artifact (params + 1e6,
  finite outputs, divergence far past the swap gate's margin) landed
  on one replica via a fresh-boot rollout — the path the golden gate
  never sees.

The ``clean`` scenario proves the other half: across ≥1 legitimate
metric flip and ≥1 verified model swap the prober raises ZERO
correctness pages, probe traffic appears in no user-facing SLO family,
the served route answer matches the scipy oracle on the replica's own
exported metric, and arming the prober adds ≤1% (with a small absolute
noise floor, recorded structurally) to serving p95.

Caches (overlay hierarchy, XLA compiles, the synthetic extract) are
shared across scenarios AND battery rounds via ``--cache-dir``
(default ``artifacts/bench_cache/probing``), so only the first run
pays the cold road-graph build.

Usage: python scripts/bench_probing.py [--quick]
       [--out artifacts/probing.json] [--cache-dir DIR]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")

# The swap gate's margin for this bench's fleet — the prober derives
# its golden tolerance from it (a model the gate would accept never
# trips the prober; one past the gate always does).
SWAP_MAX_DIV_MIN = 30.0
PROBE_INTERVAL_S = 1.0
# Probe-scale SLO windows: pages after ~5 consecutive failing rounds.
PROBE_FAST_S, PROBE_SLOW_S = 10.0, 30.0
DETECT_BOUND_S = 90.0
# Overhead gate: ≤1% of serving p95, with an absolute noise floor for
# a 1-core time-shared host (recorded structurally in the artifact).
OVERHEAD_PCT = 0.01
OVERHEAD_FLOOR_MS = 2.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _fetch(url: str, timeout: float = 30.0):
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _post(url: str, body: dict, timeout: float = 120.0):
    import urllib.request

    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def build_extract(n_nodes: int, cache_dir: str) -> str:
    """Synthetic street extract, cached across scenarios and battery
    rounds (the probe-subgraph build rides the shared warm-cache path
    — ROADMAP housekeeping: no cold hierarchy build per round)."""
    path = os.path.join(cache_dir, f"probing_{n_nodes}.osm.gz")
    if os.path.exists(path):
        return path
    from routest_tpu.data.osm import load_osm, save_osm
    from routest_tpu.data.road_graph import (generate_road_graph,
                                             subdivide_graph)
    from routest_tpu.optimize.road_router import RoadRouter

    n_int = max(512, int(n_nodes / 5.86))
    base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
    streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1,
                              seed=0)
    save_osm(path, streets)
    # Prebuild the overlay so every worker rehydrates from cache.
    t0 = time.perf_counter()
    RoadRouter(graph=load_osm(path), use_gnn=False,
               use_transformer=False)
    print(f"  overlay prebuilt in {time.perf_counter() - t0:.1f}s",
          flush=True)
    return path


class Fleet:
    """One scenario's fleet: supervisor + workers + in-process gateway
    + (optionally) broker, probe drivers, and the armed prober."""

    def __init__(self, *, live: bool, extract: str, cache_dir: str,
                 work_dir: str, replicas: int = 2,
                 drivers: int = 48, customize_s: float = 3.0,
                 probe_interval: float = PROBE_INTERVAL_S) -> None:
        from routest_tpu.core.config import (FleetConfig, ProberConfig,
                                             RecorderConfig)
        from routest_tpu.obs.recorder import (FlightRecorder,
                                              configure_recorder)
        from routest_tpu.serve.fleet.gateway import Gateway
        from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

        self.live = live
        self.work_dir = work_dir
        self.recorder_dir = os.path.join(work_dir, "postmortems")
        self.recorder = FlightRecorder(RecorderConfig(
            dir=self.recorder_dir, min_interval_s=0.0))
        configure_recorder(self.recorder)
        self.model_path = os.path.join(work_dir, "eta_serving.msgpack")
        shutil.copy(MODEL, self.model_path)
        self.broker = None
        self.probe_fleet = None
        env = dict(os.environ)
        env.update({
            "ROUTEST_FORCE_CPU": "1",
            "ROUTEST_WARM_BUCKETS": "0",
            "ROUTEST_MESH": "0",
            "ETA_MODEL_PATH": self.model_path,
            "ROUTEST_RELOAD_SEC": "0.5",
            "RTPU_SWAP_MAX_DIV": f"{SWAP_MAX_DIV_MIN:g}",
            "RTPU_RECORDER_DIR": os.path.join(work_dir, "workers"),
            "RTPU_COMPILE_CACHE": os.path.join(cache_dir, "xla"),
        })
        if live:
            from routest_tpu.serve.netbus import start_broker

            self.broker, _ = start_broker()
            env.update({
                "ROAD_GRAPH_OSM": extract,
                "ROUTEST_HIER_CACHE": os.path.join(cache_dir, "hier"),
                "REDIS_URL": f"tcp://127.0.0.1:{self.broker.port}",
                "RTPU_LIVE": "1",
                "RTPU_LIVE_CUSTOMIZE_S": f"{customize_s:g}",
                "RTPU_LIVE_HALF_LIFE_S": "10",
                "RTPU_LIVE_MIN_OBS_EDGES": "10",
            })
        self.env = env
        self.ports = [_free_port() for _ in range(replicas)]
        self.sup = ReplicaSupervisor(self.ports, env=env, cwd=REPO,
                                     probe_interval_s=0.5,
                                     backoff_base_s=0.2,
                                     backoff_cap_s=2.0)
        self.sup.start()
        if not self.sup.ready(timeout=600):
            self.sup.drain(timeout=10)
            raise RuntimeError("fleet workers never became ready")
        self.gw = Gateway([("127.0.0.1", p) for p in self.ports],
                          FleetConfig(hedge=False, max_inflight=64,
                                      queue_depth=256), supervisor=self.sup)
        self.httpd = self.gw.serve("127.0.0.1", 0)
        self.base = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        from routest_tpu.data.locations import SEED_LOCATIONS

        a, b = SEED_LOCATIONS[2], SEED_LOCATIONS[11]
        self.prober_cfg = ProberConfig(
            enabled=True, interval_s=probe_interval, timeout_s=20.0,
            eta_tolerance=SWAP_MAX_DIV_MIN,
            route_tolerance_rel=0.02,   # cross-replica EWMA drift; the
            # strict per-replica 2e-3 parity is measured separately
            routes=(f"{a[1]},{a[2]}|{b[1]},{b[2]}" if live else ""),
            skew_after=3, epoch_gap=2,
            fast_window_s=PROBE_FAST_S, slow_window_s=PROBE_SLOW_S)
        self.prober = None
        self._driver_count = drivers
        if live:
            self._wait_live_ready()

    def start_probe_drivers(self) -> None:
        from routest_tpu.data.osm import load_osm
        from routest_tpu.live.probes import ProbeFleet
        from routest_tpu.optimize.road_router import RoadRouter
        from routest_tpu.serve.netbus import NetBus

        if self.probe_fleet is not None:
            return
        router = RoadRouter(graph=load_osm(self.env["ROAD_GRAPH_OSM"]),
                            use_gnn=False, use_transformer=False)
        self.oracle_router = router
        bus = NetBus(f"tcp://127.0.0.1:{self.broker.port}")
        self.probe_fleet = ProbeFleet(router.graph_dict(),
                                      self._driver_count,
                                      bus.publish, seed=42,
                                      obs_per_tick=6)
        self.probe_fleet.start(tick_s=1.0)

    def _wait_live_ready(self, timeout: float = 300.0) -> None:
        deadline = time.time() + timeout
        for port in self.ports:
            while time.time() < deadline:
                try:
                    if _fetch(f"http://127.0.0.1:{port}/api/live",
                              timeout=10).get("ready"):
                        break
                except OSError:
                    pass
                time.sleep(0.5)
            else:
                raise RuntimeError(f"replica :{port} live never armed")

    def arm_prober(self):
        from routest_tpu.obs.prober import BlackboxProber

        self.prober = BlackboxProber(
            self.prober_cfg, gateway_base=self.base,
            targets_fn=self.gw._probe_targets, recorder=self.recorder)
        self.gw.prober = self.prober     # /api/probes surfaces it
        self.prober.start()
        return self.prober

    def replica_rids(self):
        with self.gw._lock:
            return sorted((r.id for r in self.gw.replicas
                           if not r.draining),
                          key=lambda rid: int(rid[1:]))

    def inject_replacement(self, rid: str, overlay: dict,
                           version: str) -> str:
        """Roll ONE replica onto (version, overlay); returns the
        successor's rid — the replica the prober must name."""
        from routest_tpu.serve.fleet.rollout import replace_replica

        old_port = self.ports[int(rid[1:])]
        result = replace_replica(self.sup, self.gw, rid,
                                 version=version, env=overlay,
                                 boot_timeout_s=300.0,
                                 health_timeout_s=60.0)
        if not result.get("ok"):
            raise RuntimeError(f"fault injection rollout failed: "
                               f"{result}")
        self.ports = [p for p in self.ports if p != old_port] \
            + [result["port"]]
        if self.live:
            self._wait_live_ready()
        return result["new_rid"]

    def stop(self) -> None:
        from routest_tpu.obs.recorder import configure_recorder

        if self.prober is not None:
            self.prober.stop()
        if self.probe_fleet is not None:
            self.probe_fleet.stop()
        try:
            self.gw.drain(timeout=5)
        finally:
            self.sup.drain(timeout=15)
            if self.broker is not None:
                self.broker.shutdown()
            configure_recorder(None)


def open_loop(base: str, rate: float, duration_s: float, stop=None):
    """Blocking open-loop predict_eta load (unique bodies) → records."""
    from routest_tpu.loadgen.arrivals import RateCurve, paced_schedule
    from routest_tpu.loadgen.engine import run_open_loop
    from routest_tpu.loadgen.workload import PlannedRequest

    offsets = paced_schedule(RateCurve.constant(rate), duration_s)
    requests = [PlannedRequest(
        method="POST", path="/api/predict_eta",
        body={"summary": {"distance": 7000 + i}, "weather": "Sunny",
              "traffic": "Medium", "driver_age": 33,
              "pickup_time": "2026-08-05T18:00:00"},
        route="predict_eta") for i in range(len(offsets))]
    return run_open_loop([base], offsets, requests, workers=8,
                         timeout=30.0, stop=stop)


def _p95_ms(records) -> float:
    ok = sorted(r.latency_s for r in records if 200 <= r.status < 400)
    if not ok:
        return float("nan")
    return ok[min(len(ok) - 1, int(0.95 * len(ok)))] * 1000.0


def wait_for_page(prober, bound_s: float):
    """Poll the prober's dedicated engine until any correctness
    objective pages."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < bound_s:
        snap = prober.slo.snapshot()
        for name, obj in snap["objectives"].items():
            if obj["state"] == "page":
                return {"paged": True, "objective": name,
                        "detect_s": round(time.monotonic() - t0, 2)}
        time.sleep(0.2)
    return {"paged": False, "detect_s": None}


def correctness_bundles(recorder_dir: str):
    out = []
    if not os.path.isdir(recorder_dir):
        return out
    for name in sorted(os.listdir(recorder_dir)):
        if not name.startswith("pm_") or "correctness" not in name:
            continue
        bundle = os.path.join(recorder_dir, name)
        try:
            evidence = json.load(open(
                os.path.join(bundle, "probe_evidence.json")))
            manifest = json.load(open(
                os.path.join(bundle, "manifest.json")))
        except (OSError, ValueError):
            continue
        out.append({"name": name, "evidence": evidence,
                    "manifest_reason": manifest.get("reason"),
                    "detail": manifest.get("detail")})
    return out


def judge_fault_bundle(bundles, faulty_rid: str,
                       require_dimensions=None) -> dict:
    """A correctness bundle must name the faulty replica and embed the
    probe request, served answer, oracle/pinned answer, divergence.
    ``require_dimensions`` additionally demands a skew failure on one
    of the given dimensions (e.g. the stale-epoch scenario must be
    identified AS an epoch skew, not only as a divergent answer)."""
    for b in bundles:
        ev = b["evidence"]
        if faulty_rid not in (ev.get("replicas") or []):
            continue
        for f in reversed(ev.get("failures") or []):
            named = faulty_rid in (f.get("replicas") or [])
            embedded = (f.get("request") is not None
                        and f.get("served") is not None
                        and (f.get("expected") is not None
                             or f.get("oracle") is not None
                             or f.get("dimensions") is not None))
            has_div = (f.get("divergence") is not None
                       or f.get("dimensions") is not None)
            dims = sorted(f.get("dimensions") or ())
            if require_dimensions is not None and \
                    not (set(dims) & set(require_dimensions)):
                continue
            if named and embedded and has_div:
                return {"ok": True, "bundle": b["name"],
                        "verdict": f.get("verdict"),
                        "divergence": f.get("divergence"),
                        "dimensions": dims}
    return {"ok": False,
            "bundles_seen": [b["name"] for b in bundles]}


def zero_pages(prober, recorder_dir: str) -> dict:
    snap = prober.slo.snapshot()
    states = {k: v["state"] for k, v in snap["objectives"].items()}
    return {"objective_states": states,
            "correctness_bundles": len(correctness_bundles(recorder_dir)),
            "ok": all(s == "ok" for s in states.values())
            and not correctness_bundles(recorder_dir)}


# ── scenarios ────────────────────────────────────────────────────────


def scenario_clean(extract, cache_dir, rate, quick) -> dict:
    work = tempfile.mkdtemp(prefix="probing-clean-")
    window_s = 12.0 if quick else 20.0
    out: dict = {"scenario": "clean"}
    # The clean scenario measures the STANDING cost of probing, so it
    # runs the production-shaped interval (the fault scenarios crank
    # the interval down for fast detection, a deliberate trade).
    fleet = Fleet(live=True, extract=extract, cache_dir=cache_dir,
                  work_dir=work, probe_interval=2.5)
    try:
        # (1) overhead: alternating prober-off / prober-on load
        # windows, best (min) p95 per mode — the obs-overhead bench's
        # order-drift cancellation, cheap edition. Probe DRIVERS stay
        # off for this phase (they are scenario background, not the
        # treatment variable — their ingest work swamps a 1-core
        # host's p95 in both modes); the prober warms first (oracle
        # armed, probe shapes compiled, caches primed): the claim is
        # the STANDING cost of probing, not the one-time arm cost.
        prober = fleet.arm_prober()
        time.sleep(4 * fleet.prober_cfg.interval_s)
        prober.stop()
        offs, ons = [], []
        offs.append(_p95_ms(open_loop(fleet.base, rate, window_s)))
        prober.start()
        ons.append(_p95_ms(open_loop(fleet.base, rate, window_s)))
        prober.stop()
        offs.append(_p95_ms(open_loop(fleet.base, rate, window_s)))
        prober.start()
        ons.append(_p95_ms(open_loop(fleet.base, rate, window_s)))
        p95_off, p95_on = min(offs), min(ons)
        overhead_ok = (p95_on <= p95_off * (1 + OVERHEAD_PCT)
                       or p95_on - p95_off <= OVERHEAD_FLOOR_MS)
        out["overhead"] = {
            "p95_off_ms": round(p95_off, 2),
            "p95_on_ms": round(p95_on, 2),
            "windows_off_ms": [round(v, 2) for v in offs],
            "windows_on_ms": [round(v, 2) for v in ons],
            "budget_pct": OVERHEAD_PCT * 100,
            "noise_floor_ms": OVERHEAD_FLOOR_MS,
            "ok": bool(overhead_ok),
        }

        # (2) scenario background on: probe drivers stream per-edge
        # observations so the live metric flips for real; then a
        # verified model swap mid-run — rewrite the fleet's artifact
        # with a within-gate perturbation; both replicas' reload
        # watchers land it through the golden gate.
        fleet.start_probe_drivers()
        import jax

        from routest_tpu.train.checkpoint import load_model, save_model

        model, params = load_model(fleet.model_path)
        close = jax.tree_util.tree_map(lambda x: x * (1.0 + 1e-4),
                                       params)
        save_model(fleet.model_path, model, close)
        st = os.stat(fleet.model_path)
        os.utime(fleet.model_path,
                 ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))

        def swaps_accepted() -> int:
            total = 0
            for port in fleet.ports:
                reg = _fetch(f"http://127.0.0.1:{port}/api/metrics",
                             timeout=30).get("registry", {})
                for s in reg.get("rtpu_model_swaps_total",
                                 {}).get("series", ()):
                    if s.get("labels", {}).get("result") == "accepted":
                        total += int(s.get("value", 0))
            return total

        epoch0 = max(e for e in (
            _fetch(f"http://127.0.0.1:{p}/api/live",
                   timeout=30).get("epoch", 0) for p in fleet.ports))
        deadline = time.time() + (60 if quick else 120)
        while time.time() < deadline:
            if swaps_accepted() >= 2:
                break
            time.sleep(1.0)
        # (3) ≥1 legitimate metric flip while the prober watches.
        flips = 0
        while time.time() < deadline and flips < 1:
            flips = max(e for e in (
                _fetch(f"http://127.0.0.1:{p}/api/live",
                       timeout=30).get("epoch", 0)
                for p in fleet.ports)) - epoch0
            time.sleep(1.0)
        time.sleep(5 * PROBE_INTERVAL_S)   # post-flip probe rounds
        out["swaps_accepted"] = swaps_accepted()
        out["metric_flips"] = flips

        # (4) strict per-replica oracle parity (the PR-9 invariant, as
        # the prober's own oracle computes it): served duration vs
        # scipy on the SAME replica's export.
        out["strict_oracle"] = strict_oracle_check(fleet)

        # (5) verdicts, zero pages, exclusion.
        out["final_verdicts"] = {
            k: v.get("verdict")
            for k, v in fleet.prober.snapshot()["probes"].items()}
        out["zero_pages"] = zero_pages(fleet.prober, fleet.recorder_dir)
        out["exclusion"] = exclusion_check(fleet)
        out["probe_rounds"] = fleet.prober._rounds
        checks = {
            "zero_correctness_pages": out["zero_pages"]["ok"],
            "verified_swap_ge_1": out["swaps_accepted"] >= 1,
            "metric_flip_ge_1": flips >= 1,
            "all_probes_pass_at_end": all(
                v == "pass" for v in out["final_verdicts"].values()),
            "strict_oracle_parity": out["strict_oracle"]["ok"],
            "probe_traffic_excluded": out["exclusion"]["ok"],
            "overhead_within_budget": out["overhead"]["ok"],
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


def strict_oracle_check(fleet) -> dict:
    """Served route duration ≡ scipy Dijkstra on the replica's OWN
    exported metric (epoch-stable fetch), to 2e-3 — the oracle the
    prober re-derives per flip, verified at full strictness against
    one replica (gateway-path probes tolerate cross-replica EWMA
    drift)."""
    import numpy as np
    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra

    wps = fleet.prober.route_waypoints
    replica = f"http://127.0.0.1:{fleet.ports[0]}"
    body = {"source_point": {"lat": wps[0][0], "lon": wps[0][1]},
            "destination_points": [{"lat": wps[1][0], "lon": wps[1][1],
                                    "payload": 1}],
            "driver_details": {"vehicle_type": "car",
                               "vehicle_capacity": 1e9,
                               "maximum_distance": 1e9},
            "road_graph": True}
    topo = _fetch(f"{replica}/api/debug/probe_subgraph?"
                  f"wp={wps[0][0]},{wps[0][1]}&wp={wps[1][0]},{wps[1][1]}",
                  timeout=60)
    for _attempt in range(5):
        live0 = _fetch(f"{replica}/api/live?metric=1", timeout=60)
        feat = _post(f"{replica}/api/request_route", body, timeout=120)
        live1 = _fetch(f"{replica}/api/live", timeout=60)
        if live0.get("epoch") != live1.get("epoch") \
                or "edge_time_s" not in live0:
            continue
        metric = np.asarray(live0["edge_time_s"], np.float64)
        adj = sp.coo_matrix(
            (metric, (np.asarray(topo["senders"]),
                      np.asarray(topo["receivers"]))),
            shape=(topo["nodes"], topo["nodes"])).tocsr()
        snapped = np.asarray(topo["snapped"])
        want = dijkstra(adj, directed=True, indices=snapped[:1])
        oracle_s = float(want[0, snapped[1]]) \
            + float(sum(topo["snap_m"])) / 8.3
        served_s = float(feat["properties"]["summary"]["duration"])
        rel = abs(served_s - oracle_s) / max(oracle_s, 1.0)
        return {"ok": rel < 2e-3, "epoch": live0.get("epoch"),
                "served_s": round(served_s, 2),
                "oracle_s": round(oracle_s, 2),
                "rel_err": round(rel, 6)}
    return {"ok": False, "error": "no epoch-stable window"}


def exclusion_check(fleet) -> dict:
    """Probe traffic appears in no user-facing family: the probed
    routes' user request families stay at zero while the probe
    families carry the traffic."""
    reg = _fetch(f"{fleet.base}/api/metrics", timeout=30)["registry"]

    def family(name):
        return {tuple(s.get("labels", {}).values()):
                s.get("value", s.get("count", 0))
                for s in reg.get(name, {}).get("series", ())}

    user = family("rtpu_gateway_request_seconds")
    probe = family("rtpu_probe_gateway_requests_total")
    probed_routes = ["/api/predict_eta_batch", "/api/request_route",
                     "/api/matrix"]
    leaked = {r: user.get((r,), 0) for r in probed_routes
              if user.get((r,), 0)}
    carried = sum(probe.get((r,), 0) for r in probed_routes)
    return {"ok": not leaked and carried > 0,
            "leaked_user_counts": leaked,
            "probe_family_count": carried,
            "user_predict_eta_count":
                user.get(("/api/predict_eta",), 0)}


def scenario_fault(name, extract, cache_dir, rate, quick, *,
                   live, overlay=None, corrupt_model=False,
                   expect_dimensions=None) -> dict:
    """Shared fault harness: boot → arm → baseline all-pass → inject
    via replace_replica → page within bound → bundle names replica."""
    work = tempfile.mkdtemp(prefix=f"probing-{name}-")
    out: dict = {"scenario": name}
    fleet = Fleet(live=live, extract=extract, cache_dir=cache_dir,
                  work_dir=work)
    load_stop = threading.Event()
    try:
        if live:
            fleet.start_probe_drivers()
        prober = fleet.arm_prober()
        # Light background load for realism (user SLO must stay ok).
        def _load():
            while not load_stop.is_set():
                try:
                    open_loop(fleet.base, rate, 10.0, stop=load_stop)
                except Exception:
                    pass

        load_thread = threading.Thread(target=_load, daemon=True)
        load_thread.start()
        baseline_deadline = time.time() + (30 if quick else 60)
        while time.time() < baseline_deadline:
            snap = prober.snapshot()["probes"]
            if snap and all(v.get("verdict") == "pass"
                            for v in snap.values()):
                break
            time.sleep(1.0)
        out["baseline_verdicts"] = {
            k: v.get("verdict")
            for k, v in prober.snapshot()["probes"].items()}
        overlay = dict(overlay or {})
        if corrupt_model:
            import jax

            from routest_tpu.train.checkpoint import (load_model,
                                                      save_model)

            # ×1.5-scaled weights: outputs stay finite and plausibly
            # sized (median ~100 min off, no timestamp overflow — the
            # replica keeps answering clean 200s) yet sit far past the
            # swap gate's margin. The corrupt-ISH artifact: wrong, not
            # broken.
            model, params = load_model(fleet.model_path)
            garbage = jax.tree_util.tree_map(lambda x: x * 1.5, params)
            bad_path = os.path.join(work, "eta_bad.msgpack")
            save_model(bad_path, model, garbage)
            overlay["ETA_MODEL_PATH"] = bad_path
        victim = fleet.replica_rids()[0]
        t_fault = time.time()
        faulty_rid = fleet.inject_replacement(victim, overlay,
                                              version=f"v-{name}")
        out.update({"victim": victim, "faulty_rid": faulty_rid,
                    "inject_wall_s": round(time.time() - t_fault, 1)})
        page = wait_for_page(prober, DETECT_BOUND_S)
        out["page"] = page
        out["detect_bound_s"] = DETECT_BOUND_S
        # The FIRST page may come from a probe kind that names the
        # replica indirectly (a gateway-path divergence carries the
        # serving replica; the fan-out skew verdict lands a few
        # debounce rounds later) — poll until a bundle naming the
        # faulty replica exists, still inside the detection bound.
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            bundles = correctness_bundles(fleet.recorder_dir)
            out["bundle"] = judge_fault_bundle(
                bundles, faulty_rid,
                require_dimensions=expect_dimensions)
            if out["bundle"]["ok"]:
                break
            time.sleep(1.0)
        if expect_dimensions:
            dims = set(out["bundle"].get("dimensions") or ())
            out["bundle"]["expected_dimensions_seen"] = \
                bool(dims & set(expect_dimensions))
        # User SLO must be untouched by the correctness incident (the
        # replica answered 200s throughout).
        gw_slo = fleet.gw.slo
        if gw_slo is not None:
            gw_slo.tick()
            out["user_slo_state"] = gw_slo.worst_state()
        checks = {
            "detected_and_paged": bool(page["paged"]),
            "within_bound": bool(page["paged"]
                                 and page["detect_s"] <= DETECT_BOUND_S),
            "bundle_names_faulty_replica": out["bundle"]["ok"],
            "user_slo_ok": out.get("user_slo_state", "ok") == "ok",
        }
        if expect_dimensions:
            checks["skew_dimension_identified"] = \
                out["bundle"].get("expected_dimensions_seen", False)
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        load_stop.set()
        # Join BEFORE teardown: late client requests against a
        # draining gateway would record 503s into the GLOBAL gateway
        # families and poison the next scenario's user-SLO engine.
        try:
            load_thread.join(timeout=20)
        except (NameError, RuntimeError):
            pass
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="smaller extract + shorter phases (CI)")
    parser.add_argument("--nodes", type=int, default=6000)
    parser.add_argument("--rate", type=float, default=3.0)
    parser.add_argument("--cache-dir", default=os.path.join(
        REPO, "artifacts", "bench_cache", "probing"))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "probing.json"))
    parser.add_argument("--scenario", default=None,
                        help="run one scenario (debug)")
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 4000)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(args.cache_dir, exist_ok=True)
    os.environ["ROUTEST_HIER_CACHE"] = os.path.join(args.cache_dir,
                                                    "hier")
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache(os.path.join(args.cache_dir, "xla"))
    os.environ["RTPU_SWAP_MAX_DIV"] = f"{SWAP_MAX_DIV_MIN:g}"

    t0 = time.time()
    print(f"[1/5] extract + overlay cache ({args.nodes:,} nodes)…",
          flush=True)
    extract = build_extract(args.nodes, args.cache_dir)

    scenarios: dict = {}
    plan = [
        ("clean", lambda: scenario_clean(
            extract, args.cache_dir, args.rate, args.quick)),
        ("compute_divergence", lambda: scenario_fault(
            "compute_divergence", extract, args.cache_dir, args.rate,
            args.quick, live=False,
            overlay={"RTPU_CHAOS_SPEC": "device.compute:skew=1.0/60",
                     "RTPU_CHAOS_SEED": "7"})),
        ("stale_epoch", lambda: scenario_fault(
            "stale_epoch", extract, args.cache_dir, args.rate,
            args.quick, live=True,
            overlay={"RTPU_CHAOS_SPEC": "live.customize:error=1.0",
                     "RTPU_CHAOS_SEED": "7"},
            expect_dimensions=("epoch",))),
        ("divergent_model", lambda: scenario_fault(
            "divergent_model", extract, args.cache_dir, args.rate,
            args.quick, live=False, corrupt_model=True)),
    ]
    for i, (name, run) in enumerate(plan):
        if args.scenario and name != args.scenario:
            continue
        print(f"[{i + 2}/5] scenario {name}…", flush=True)
        t = time.perf_counter()
        try:
            scenarios[name] = run()
        except Exception as e:
            scenarios[name] = {"scenario": name, "pass": False,
                               "error": f"{type(e).__name__}: {e}"}
        scenarios[name]["wall_s"] = round(time.perf_counter() - t, 1)
        print(f"  {name}: "
              f"{'PASS' if scenarios[name].get('pass') else 'FAIL'} "
              f"({scenarios[name]['wall_s']}s)", flush=True)

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    backend = jax.devices()[0].platform
    record = {
        "generated_unix": int(t0),
        "host": {"cpus": n_cpus, "platform": sys.platform,
                 "backend": backend},
        # Structural caveats (ROADMAP housekeeping: skip reasons are
        # fields, never prose in `note`): detection windows and the
        # overhead floor are host-scaled; the invariants (detected →
        # paged → bundle names replica; clean stays green) are not.
        "host_caveat": (
            f"cpu-backend record on {n_cpus} core(s): detection "
            "latencies and p95s are time-shared-host numbers; judge "
            "the structural checks (paged within bound, bundle names "
            "the replica, clean run green, exclusion exact), not "
            "wall-ms" if backend != "tpu" else None),
        "skipped": ("tpu probe: CPU fallback rows — re-record when a "
                    "tunnel appears (scripts/run_tpu_battery.sh does "
                    "it automatically)" if backend != "tpu" else None),
        "config": {
            "nodes": args.nodes, "rate_rps": args.rate,
            "probe_interval_s": PROBE_INTERVAL_S,
            "probe_fast_s": PROBE_FAST_S,
            "probe_slow_s": PROBE_SLOW_S,
            "swap_gate_margin_min": SWAP_MAX_DIV_MIN,
            "detect_bound_s": DETECT_BOUND_S,
            "overhead_budget_pct": OVERHEAD_PCT * 100,
            "overhead_noise_floor_ms": OVERHEAD_FLOOR_MS,
            "cache_dir": args.cache_dir,
            "quick": bool(args.quick),
        },
        "scenarios": scenarios,
    }
    if args.scenario:
        record["partial"] = f"--scenario {args.scenario} (debug run)"
    record["checks"] = {name: bool(s.get("pass"))
                        for name, s in scenarios.items()}
    record["all_pass"] = (bool(record["checks"])
                          and all(record["checks"].values())
                          and (args.scenario is not None
                               or len(scenarios) == 4))
    record["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"\n[5/5] checks: "
          + " ".join(f"{k}={'PASS' if v else 'FAIL'}"
                     for k, v in record["checks"].items())
          + f"\n→ {args.out} (all_pass={record['all_pass']}, "
            f"{record['wall_s']}s)", flush=True)
    # _exit, not sys.exit: probe-driver daemon threads racing
    # interpreter teardown must not turn a written verdict into a
    # crash (same contract as bench_live_traffic).
    os._exit(0 if record["all_pass"] else 1)


if __name__ == "__main__":
    main()
