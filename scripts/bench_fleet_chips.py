"""Per-chip fleet scaling: the chips={1,2,4,8} preds/s curve plus the
8-chip placement comparison (8×1 vs 2×4 vs 1×8) — topology-aware
placement proven end to end.

PR 10 made the compute side multi-chip (the AOT scoring artifact
compiles under mesh batch shardings) but nothing fleet-side ever
*placed* more than one chip, so BASELINE's ≥10k preds/s/chip was
unmeasurable per chip. This bench pins the whole shape on virtual
devices (``XLA_FLAGS --xla_force_host_platform_device_count``) so it
runs identically the moment real hardware shows up:

1. **curve** — ONE replica pinned to k ∈ {1,2,4,8} chips via the
   placement overlay machinery (``serve/fleet/placement.slice_env``;
   multi-chip slices serve with the mesh batch sharding), driven with
   ``/api/predict_eta_batch`` through a real gateway → preds/s,
   preds/s/chip, and per-chip efficiency.
2. **placements** — three fleets spending the SAME 8 chips (8×1-chip,
   2×4-chip, 1×8-chip), same offered load → preds/s + client errors,
   with every placement's scores checked against the single-replica
   scorer oracle (the chips=1 fleet's response to one fixed batch).
3. **weighted_routing** — a mixed-capacity gateway (no processes):
   capacity-normalized least-outstanding must spread held work in
   proportion to capacity (a 4-unit upstream absorbs ~4× a 1-unit one).
4. **rolling_restart** — the 2×4 fleet restarts under live traffic;
   zero client errors and every successor keeps its predecessor's
   device overlay (placement label + chip count via
   ``checks.engine.mesh``).

Honesty: virtual chips TIME-SHARE the host's cores, so raw preds/s
cannot grow past the core count — ``host_caveat`` (structural, PR
10/11 convention) says so, and ``efficiency`` normalizes by
``chips_effective = min(chips, cores)`` on the CPU backend (= chips on
real accelerators, where the field becomes the honest per-chip claim).

Usage: python scripts/bench_fleet_chips.py [--quick]
       [--chips 1 2 4 8] [--out artifacts/fleet_chips.json]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import socket
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from routest_tpu.serve.fleet.placement import (  # noqa: E402
    PLACEMENT_LABEL_ENV, slice_env)

FIXED_BATCH = 256      # rows in the oracle batch (deterministic body)


def _load_load_test():
    spec = importlib.util.spec_from_file_location(
        "load_test", os.path.join(REPO, "scripts", "load_test.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(base, path, payload, timeout=180.0):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(base, path, timeout=15.0):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def _fixed_batch_payload():
    # Deterministic body: the SAME rows go through every placement, so
    # responses are directly comparable to the single-replica oracle.
    return {
        "distance_m": [500.0 + 153.0 * i for i in range(FIXED_BATCH)],
        "weather": "Cloudy",
        "traffic": [("Low", "Medium", "High", "Jam")[i % 4]
                    for i in range(FIXED_BATCH)],
        "driver_age": [25.0 + (i % 30) for i in range(FIXED_BATCH)],
        "pickup_time": "2026-08-05T08:30:00",
    }


def boot_layout(layout, warm_batch: int):
    """Boot one real-worker fleet with per-replica device pinning:
    ``layout`` is a list of per-replica chip counts (virtual CPU
    devices; multi-chip slices serve mesh-sharded). → (supervisor,
    gateway, base_url, ports)."""
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    ports = [_free_port() for _ in layout]
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        # The fastlane cache would serve the repeated oracle batch from
        # memory — this bench measures the DEVICE path per chip.
        "RTPU_FASTLANE_CACHE": "0",
        "ETA_MODEL_PATH": os.path.join(REPO, "artifacts",
                                       "eta_mlp.msgpack"),
    })
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    # Pin each replica's slice by hand (the same overlays
    # plan_placement emits for a forced layout on this platform).
    next_id = 0
    for i, (r, k) in enumerate(zip(sup._replicas, layout)):
        ids = tuple(range(next_id, next_id + k))
        next_id += k
        label = f"s{i}:{k}chip"
        r.placement_env = slice_env("cpu", k, ids, label)
        r.chips, r.capacity, r.placement_label = k, float(k), label
    sup.start()
    if not sup.ready(timeout=600):
        sup.drain(timeout=10)
        raise RuntimeError(f"layout {layout}: workers never ready")
    for port in ports:   # warm every replica's device path directly
        base = f"http://127.0.0.1:{port}"
        _post(base, "/api/predict_eta_batch",
              {"distance_m": [1000.0] * warm_batch})
        _post(base, "/api/predict_eta_batch", _fixed_batch_payload())
    gw = Gateway([("127.0.0.1", p) for p in ports],
                 FleetConfig(hedge=False, eject_after=3, cooldown_s=1.0,
                             max_inflight=64, queue_depth=256),
                 supervisor=sup)
    for i, k in enumerate(layout):
        gw.set_topology(f"r{i}", chips=k)
    httpd = gw.serve("127.0.0.1", 0)
    return sup, gw, f"http://127.0.0.1:{httpd.server_address[1]}", ports


def replica_mesh(port: int) -> dict:
    health = _get(f"http://127.0.0.1:{port}", "/api/health")
    return ((health.get("checks") or {}).get("engine") or {}).get(
        "mesh") or {}


def run_curve(chips_list, lt, args, cores):
    rows = []
    oracle = None
    for k in chips_list:
        print(f"[bench_fleet_chips] === curve: {k} chip(s) ===",
              file=sys.stderr)
        sup, gw, base, ports = boot_layout([k], args.batch_size)
        try:
            mesh = replica_mesh(ports[0])
            if mesh.get("devices") != k:
                raise RuntimeError(
                    f"placement overlay failed: wanted {k} devices, "
                    f"replica reports {mesh}")
            t0 = time.time()
            batch, errs = lt.run_batch_load([base], args.batch_threads,
                                            args.batch_requests,
                                            args.batch_size)
            status, body = _post(base, "/api/predict_eta_batch",
                                 _fixed_batch_payload())
            fixed = body.get("eta_minutes_ml") or []
            row = {
                "chips": k,
                "preds_per_s": batch["preds_per_s"],
                "preds_per_s_per_chip": round(
                    (batch["preds_per_s"] or 0.0) / k, 1),
                "mesh": mesh,
                "sharded": bool(mesh.get("sharded")),
                "p50_ms": batch.get("p50_ms"),
                "p95_ms": batch.get("p95_ms"),
                "client_errors": len(errs) + (0 if status == 200 else 1),
                "wall_seconds": round(time.time() - t0, 1),
            }
            if k == 1:
                oracle = fixed
                row["oracle"] = "this row IS the single-replica oracle"
            rows.append((row, fixed))
            print(f"[bench_fleet_chips] {k} chip(s): "
                  f"{row['preds_per_s']} preds/s", file=sys.stderr)
        finally:
            gw.drain(timeout=10)
            sup.drain(timeout=20)
    base_rate = rows[0][0]["preds_per_s"] or 1.0
    out = []
    for row, fixed in rows:
        k = row["chips"]
        k_eff = min(k, cores)
        row["chips_effective"] = k_eff
        row["efficiency"] = round(
            (row["preds_per_s"] or 0.0) / (k_eff * base_rate), 3)
        # Projected = what this row would deliver if every virtual
        # chip were a real core at the MEASURED per-sharded-chip rate
        # (= measured preds/s exactly when chips_effective == chips,
        # i.e. on real hardware). The curve's monotone claim binds on
        # this, structurally, on any host.
        row["preds_per_s_projected"] = round(
            (row["preds_per_s"] or 0.0) * k / k_eff, 1)
        if oracle and row.get("oracle") is None:
            row["oracle_max_abs_diff"] = _max_abs_diff(fixed, oracle)
        out.append(row)
    return out, oracle


def _max_abs_diff(a, b) -> float:
    if not a or not b or len(a) != len(b):
        return float("inf")
    return round(max(abs(float(x) - float(y)) for x, y in zip(a, b)), 9)


def run_placements(layouts, oracle, lt, args):
    rows = []
    for layout in layouts:
        name = "+".join(str(k) for k in layout) if len(set(layout)) > 1 \
            else f"{len(layout)}x{layout[0]}"
        print(f"[bench_fleet_chips] === placement {name} ===",
              file=sys.stderr)
        sup, gw, base, ports = boot_layout(layout, args.batch_size)
        try:
            t0 = time.time()
            batch, errs = lt.run_batch_load(
                [base], args.batch_threads, args.batch_requests,
                args.batch_size)
            status, body = _post(base, "/api/predict_eta_batch",
                                 _fixed_batch_payload())
            fixed = body.get("eta_minutes_ml") or []
            snap = gw.snapshot()
            rows.append({
                "layout": name,
                "replicas": len(layout),
                "chips_total": sum(layout),
                "capacity_units": snap["fleet"]["capacity_units"],
                "preds_per_s": batch["preds_per_s"],
                "p95_ms": batch.get("p95_ms"),
                "client_errors": len(errs) + (0 if status == 200 else 1),
                "per_replica_requests": {
                    rid: r["requests"]
                    for rid, r in snap["replicas"].items()},
                "oracle_max_abs_diff": _max_abs_diff(fixed, oracle),
                "wall_seconds": round(time.time() - t0, 1),
            })
            print(f"[bench_fleet_chips] {name}: "
                  f"{rows[-1]['preds_per_s']} preds/s, oracle diff "
                  f"{rows[-1]['oracle_max_abs_diff']}", file=sys.stderr)
        finally:
            gw.drain(timeout=10)
            sup.drain(timeout=20)
    return rows


def run_weighted_routing(picks: int = 500) -> dict:
    """No processes: a gateway holding work must spread HELD
    outstanding in proportion to advertised capacity. 500 picks, none
    completed — a capacity-4 upstream should hold ~4× a capacity-1."""
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.serve.fleet.gateway import Gateway

    capacities = [4.0, 2.0, 1.0, 1.0]
    gw = Gateway([("127.0.0.1", 10000 + i)
                  for i in range(len(capacities))],
                 FleetConfig(hedge=False))
    for i, cap in enumerate(capacities):
        gw.set_topology(f"r{i}", chips=int(cap), capacity=cap)
    for _ in range(picks):
        r = gw._pick()
        assert r is not None
    with gw._lock:
        held = {r.id: r.outstanding for r in gw.replicas}
    total_cap = sum(capacities)
    shares = {}
    ok = True
    for i, cap in enumerate(capacities):
        want = cap / total_cap
        got = held[f"r{i}"] / picks
        shares[f"r{i}"] = {"capacity": cap, "picks": held[f"r{i}"],
                           "share": round(got, 3),
                           "want_share": round(want, 3)}
        ok = ok and abs(got - want) <= 0.10
    return {"picks": picks, "shares": shares,
            "within_10pct_of_capacity": ok}


def run_rolling_restart(lt, args) -> dict:
    """The 2×4 fleet restarts under live single-row traffic: zero
    client errors, and each successor must report the SAME placement
    label + device count its predecessor owned (the overlay survives
    the rollout machinery)."""
    from routest_tpu.serve.fleet.rollout import rolling_restart

    sup, gw, base, ports = boot_layout([4, 4], args.batch_size)
    errors = []
    count = [0]
    stop = threading.Event()
    payload = {"summary": {"distance": 12_000}, "weather": "Sunny",
               "traffic": "Medium", "driver_age": 35,
               "pickup_time": "2026-08-05T08:30:00"}

    def pump():
        while not stop.is_set():
            try:
                status, _ = _post(base, "/api/predict_eta", payload,
                                  timeout=60)
                count[0] += 1
                if status >= 500:
                    errors.append(status)
            except Exception as e:
                errors.append(str(e)[:80])

    try:
        before = {f"r{i}": replica_mesh(p) for i, p in enumerate(ports)}
        threads = [threading.Thread(target=pump, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        out = rolling_restart(sup, gw, version="chips-bench-v2",
                              env={"RTPU_VERSION": "chips-bench-v2"},
                              max_unavailable=1, drain_timeout_s=10.0,
                              boot_timeout_s=600.0,
                              health_timeout_s=30.0)
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join(timeout=90)
        after = {}
        with sup._lock:
            live = [(r.index, r.port, r.placement_label, r.chips)
                    for r in sup._replicas if not r.retired]
        for index, port, label, chips_n in live:
            after[f"r{index}"] = {"label": label, "chips": chips_n,
                                  "mesh": replica_mesh(port)}
        preserved = (
            sorted((v["label"], v["chips"]) for v in after.values())
            == sorted((m.get("placement"), m.get("devices"))
                      for m in before.values())
            and all(v["mesh"].get("devices") == v["chips"]
                    for v in after.values()))
        return {
            "restart_ok": bool(out.get("ok")),
            "replaced": len(out.get("replaced", [])),
            "requests_during": count[0],
            "client_errors": len(errors),
            "errors_sample": errors[:5],
            "overlay_before": {k: {"placement": m.get("placement"),
                                   "devices": m.get("devices")}
                               for k, m in before.items()},
            "overlay_after": {k: {"placement": v["label"],
                                  "devices": v["mesh"].get("devices")}
                              for k, v in after.items()},
            "overlay_preserved": bool(preserved),
        }
    finally:
        stop.set()
        gw.drain(timeout=10)
        sup.drain(timeout=20)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--chips", type=int, nargs="+",
                        default=[1, 2, 4, 8])
    parser.add_argument("--batch-size", type=int, default=2048,
                        help="OD pairs per predict_eta_batch request")
    parser.add_argument("--batch-requests", type=int, default=8,
                        help="batch requests per client thread")
    parser.add_argument("--batch-threads", type=int, default=4)
    parser.add_argument("--skip-restart", action="store_true")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "fleet_chips.json"))
    args = parser.parse_args()
    if args.quick:
        args.batch_requests, args.batch_threads = 3, 2
        args.batch_size = min(args.batch_size, 1024)

    lt = _load_load_test()
    cores = len(os.sched_getaffinity(0)) \
        if hasattr(os, "sched_getaffinity") else (os.cpu_count() or 1)
    try:
        import jax

        backend = jax.default_backend()
    except Exception:
        backend = "cpu"

    curve, oracle = run_curve(args.chips, lt, args, cores)
    max_chips = max(args.chips)
    layouts = [[1] * max_chips,
               [max_chips // 2] * 2 if max_chips >= 2 else [1],
               [max_chips]]
    placements = run_placements(layouts, oracle, lt, args)
    weighted = run_weighted_routing()
    restart = None if args.skip_restart else run_rolling_restart(lt, args)

    report = {
        "recorded_unix": int(time.time()),
        "host": {"cpu_count": cores, "backend": backend,
                 "multi_core": cores > 1},
        # Structural caveat (PR 10/11 convention; the ROADMAP
        # housekeeping item: NOT a free-text note) — None only on a
        # real accelerator backend.
        "host_caveat": (None if backend == "tpu" else
                        f"cpu-backend record on {cores} core(s): "
                        "virtual chips time-share the host, so raw "
                        "preds/s cannot grow past the core count; "
                        "'efficiency' normalizes by chips_effective = "
                        "min(chips, cores) and becomes the true "
                        "per-chip efficiency on real hardware — "
                        "re-record there (PERFORMANCE.md §8)"),
        "efficiency_basis": {
            "chips_effective": "min(chips, host cores) on cpu; chips "
                               "on real accelerators",
            "formula": "preds_per_s / (chips_effective * "
                       "preds_per_s[chips=1])",
        },
        "oracle": {"batch_rows": FIXED_BATCH,
                   "source": "chips=1 single-replica response to the "
                             "fixed deterministic batch"},
        "curve": curve,
        "placements": placements,
        "weighted_routing": weighted,
        "rolling_restart": restart,
    }
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps({k: report[k] for k in
                      ("host", "host_caveat", "curve", "placements",
                       "weighted_routing")}, indent=2))
    if restart is not None:
        print(json.dumps({"rolling_restart": {
            k: restart[k] for k in ("restart_ok", "client_errors",
                                    "overlay_preserved")}}, indent=2))
    print(f"[bench_fleet_chips] report → {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
