"""Emit the curated Metro Manila arterial extract (OSM XML, gzipped).

VERDICT r4 next #6 asks for a real road network (the reference rides
real streets through ORS — ``Flaskr/utils.py:97-103``; SURVEY §7.3.5
asks for a Metro Manila extract). This sandbox has zero egress, so an
ODbL database dump cannot be fetched; this script instead encodes the
city's arterial network from public-knowledge geography:

- REAL roads (EDSA, Quezon Ave, Commonwealth, España, Aurora, Ortigas,
  Shaw, C-5, Ayala, Gil Puyat, Taft, Roxas, Osmeña, ...), their REAL
  junction topology, and real-world tagging (trunk/primary/secondary
  classes, km/h maxspeeds, the Welcome Rotonda and Quezon Memorial
  Circle as ``junction=roundabout`` rings, a one-way pair in the Makati
  CBD, a ``PH:urban`` zone maxspeed, Ñ entity references in names);
- junction coordinates curated to roughly ±300 m (good enough for
  haversine edge lengths to be city-realistic);
- way geometry densified by interpolating shape points every ~75 m
  between junctions (straight chords — the one synthetic aspect, and
  the reason this is labeled "curated", not "extracted").

The emitted file also carries the real-extract furniture parsers must
tolerate: ``<bounds>``, a ``<relation>`` (the EDSA Carousel bus route),
XML comments, a way clipped at the extract boundary (a ``<nd>`` ref
with no node), and a non-drivable footway.

Output: ``artifacts/manila_arterials.osm.gz`` (deterministic bytes —
re-running reproduces the committed artifact exactly).
``tests/test_manila_extract.py`` pins parser parity + routing on it.
"""

from __future__ import annotations

import argparse
import gzip
import io
import math
import os

# ── curated junction table: name → (lat, lon) ─────────────────────────
# Approximate real coordinates (±~300 m) of the named intersections.
JUNCTIONS = {
    # EDSA (C-4) from the Bonifacio Monument to the Roxas Blvd end
    "monumento": (14.6565, 120.9840),
    "balintawak": (14.6575, 121.0040),
    "munoz": (14.6578, 121.0185),
    "north_edsa": (14.6527, 121.0321),
    "quezon_edsa": (14.6424, 121.0384),
    "kamuning": (14.6351, 121.0414),
    "cubao": (14.6197, 121.0525),
    "santolan": (14.6077, 121.0565),
    "ortigas_edsa": (14.5907, 121.0567),
    "shaw_edsa": (14.5812, 121.0534),
    "guadalupe": (14.5669, 121.0457),
    "buendia_edsa": (14.5539, 121.0343),
    "ayala_edsa": (14.5495, 121.0277),
    "magallanes": (14.5374, 121.0190),
    "taft_edsa": (14.5377, 121.0010),
    "roxas_edsa": (14.5352, 120.9830),
    # España → Welcome Rotonda (ring nodes) → Quezon Ave
    "lerma": (14.6038, 120.9866),
    "espana_lacson": (14.6096, 120.9934),
    "rotonda_n": (14.6183, 121.0048),
    "rotonda_e": (14.6178, 121.0054),
    "rotonda_s": (14.6173, 121.0048),
    "rotonda_w": (14.6178, 121.0042),
    "timog_quezon": (14.6333, 121.0255),
    # Quezon Memorial Circle ring
    "qmc_s": (14.6488, 121.0493),
    "qmc_e": (14.6515, 121.0523),
    "qmc_n": (14.6542, 121.0493),
    "qmc_w": (14.6515, 121.0463),
    "philcoa": (14.6549, 121.0521),
    "tandang_sora": (14.6714, 121.0665),
    "fairview": (14.6902, 121.0770),
    # New Manila / Cubao east
    "erod_araneta": (14.6208, 121.0174),
    "erod_gilmore": (14.6192, 121.0330),
    "gilmore_aurora": (14.6133, 121.0333),
    "anonas": (14.6245, 121.0646),
    "katipunan_aurora": (14.6316, 121.0744),
    # Ortigas / Mandaluyong
    "ortigas_meralco": (14.5880, 121.0640),
    "ortigas_c5": (14.5860, 121.0777),
    "shaw_kalentong": (14.5838, 121.0300),
    "shaw_meralco": (14.5830, 121.0570),
    # C-5 corridor
    "c5_erod_jr": (14.6100, 121.0800),
    "c5_kalayaan": (14.5496, 121.0553),
    "c5_slex": (14.5130, 121.0360),
    # Makati CBD
    "ayala_makati": (14.5528, 121.0242),
    "ayala_paseo": (14.5548, 121.0220),
    "ayala_buendia": (14.5577, 121.0190),
    "buendia_makati": (14.5552, 121.0292),
    "buendia_paseo": (14.5562, 121.0251),
    "buendia_chino": (14.5590, 121.0145),
    "buendia_osmena": (14.5620, 121.0040),
    "buendia_taft": (14.5637, 120.9950),
    "roxas_buendia": (14.5566, 120.9889),
    # Manila proper
    "taft_cityhall": (14.5895, 120.9817),
    "taft_quirino": (14.5705, 120.9893),
    "taft_libertad": (14.5500, 120.9985),
    "roxas_luneta": (14.5790, 120.9758),
    "roxas_quirino": (14.5702, 120.9832),
    "quirino_osmena": (14.5790, 121.0020),
    # footway endpoints (non-drivable, must be excluded by the parser)
    "promenade_a": (14.5825, 120.9760),
    "promenade_b": (14.5805, 120.9745),
}

# ── curated way table ─────────────────────────────────────────────────
# (name [raw XML text: may carry entity refs], [junctions...], tags)
WAYS = [
    ("Epifanio de los Santos Avenue",
     ["monumento", "balintawak", "munoz", "north_edsa", "quezon_edsa",
      "kamuning", "cubao", "santolan", "ortigas_edsa", "shaw_edsa",
      "guadalupe", "buendia_edsa", "ayala_edsa", "magallanes",
      "taft_edsa", "roxas_edsa"],
     {"highway": "trunk", "ref": "C-4", "maxspeed": "60"}),
    ("Espa&#241;a Boulevard",          # Ñ as a numeric entity reference
     ["lerma", "espana_lacson", "rotonda_s"],
     {"highway": "primary", "maxspeed": "40"}),
    ("Welcome Rotonda",
     ["rotonda_n", "rotonda_e", "rotonda_s", "rotonda_w", "rotonda_n"],
     {"highway": "primary", "junction": "roundabout"}),
    ("Quezon Avenue",
     ["rotonda_n", "timog_quezon", "quezon_edsa", "qmc_s"],
     {"highway": "primary", "maxspeed": "60"}),
    ("Elliptical Road",
     ["qmc_s", "qmc_e", "qmc_n", "qmc_w", "qmc_s"],
     {"highway": "primary", "junction": "roundabout"}),
    ("Commonwealth Avenue",
     ["qmc_n", "philcoa", "tandang_sora", "fairview"],
     {"highway": "primary", "maxspeed": "60"}),
    ("North Avenue",
     ["north_edsa", "qmc_w"],
     {"highway": "secondary"}),
    ("Eulogio Rodriguez Sr. Avenue",
     ["rotonda_e", "erod_araneta", "erod_gilmore"],
     {"highway": "secondary"}),
    ("Gilmore Avenue",
     ["erod_gilmore", "gilmore_aurora"],
     {"highway": "secondary", "maxspeed": "40 km/h"}),
    ("Aurora Boulevard",
     ["gilmore_aurora", "cubao", "anonas", "katipunan_aurora"],
     {"highway": "primary", "maxspeed": "50"}),
    ("Ortigas Avenue",
     ["ortigas_edsa", "ortigas_meralco", "ortigas_c5"],
     {"highway": "primary", "maxspeed": "50"}),
    ("Shaw Boulevard",
     ["shaw_kalentong", "shaw_edsa", "shaw_meralco"],
     {"highway": "secondary", "maxspeed": "40"}),
    ("Circumferential Road 5",
     ["katipunan_aurora", "c5_erod_jr", "ortigas_c5", "c5_kalayaan",
      "c5_slex"],
     {"highway": "trunk", "ref": "C-5", "maxspeed": "60"}),
    ("Ayala Avenue",
     ["ayala_edsa", "ayala_makati", "ayala_paseo", "ayala_buendia"],
     {"highway": "primary", "maxspeed": "40"}),
    # CBD one-way pair: one drawn WITH the signed direction, one
    # against it (oneway=-1) — both real tagging variants. The signed
    # directions here are approximations (see module docstring).
    ("Paseo de Roxas",
     ["ayala_paseo", "buendia_paseo"],
     {"highway": "secondary", "oneway": "yes"}),
    ("Makati Avenue",
     ["ayala_makati", "buendia_makati"],
     {"highway": "secondary", "oneway": "-1"}),
    ("Senator Gil Puyat Avenue",
     ["buendia_edsa", "buendia_makati", "buendia_paseo",
      "ayala_buendia", "buendia_chino", "buendia_osmena",
      "buendia_taft", "roxas_buendia"],
     {"highway": "primary", "maxspeed": "50"}),
    ("Taft Avenue",
     ["taft_cityhall", "taft_quirino", "buendia_taft", "taft_libertad",
      "taft_edsa"],
     {"highway": "primary", "maxspeed": "40"}),
    ("Roxas Boulevard",
     ["roxas_luneta", "roxas_quirino", "roxas_buendia", "roxas_edsa"],
     {"highway": "primary", "maxspeed": "60"}),
    ("President Quirino Avenue",
     ["roxas_quirino", "taft_quirino", "quirino_osmena"],
     {"highway": "secondary", "maxspeed": "PH:urban"}),  # zone ref →
    # class-default fallback in both parsers
    ("Osme&#241;a Highway",
     ["quirino_osmena", "buendia_osmena", "magallanes"],
     {"highway": "trunk", "maxspeed": "60"}),
    # non-drivable: excluded by the highway-class filter
    ("Rizal Park Promenade",
     ["promenade_a", "promenade_b"],
     {"highway": "footway"}),
]

SPACING_M = 75.0  # shape-point interpolation interval


def _haversine_m(a, b) -> float:
    r = math.pi / 180.0
    s = (math.sin((b[0] - a[0]) * r / 2) ** 2
         + math.cos(a[0] * r) * math.cos(b[0] * r)
         * math.sin((b[1] - a[1]) * r / 2) ** 2)
    return 2 * 6371008.8 * math.asin(math.sqrt(s))


def build_xml() -> str:
    out = io.StringIO()
    w = out.write
    w('<?xml version="1.0" encoding="UTF-8"?>\n')
    w('<osm version="0.6" generator="routest_tpu '
      'scripts/make_manila_extract.py">\n')
    w('  <!-- Curated Metro Manila arterial network: real roads and\n'
      '       junction topology from public-knowledge geography\n'
      '       (coordinates +/-300 m, shape points interpolated).\n'
      '       NOT an OpenStreetMap database extract. -->\n')
    w('  <bounds minlat="14.50" minlon="120.95" maxlat="14.70" '
      'maxlon="121.10"/>\n')

    node_ids = {}  # junction name → xml id
    next_id = 1
    for name, (lat, lon) in JUNCTIONS.items():
        node_ids[name] = next_id
        w(f'  <node id="{next_id}" lat="{lat:.7f}" lon="{lon:.7f}"/>\n')
        next_id += 1

    # Densified ways: interpolate shape nodes between junctions so edge
    # granularity matches a real extract's bend-per-vertex geometry.
    way_id = 100000
    shape_rows = []   # deferred <node> rows for shape points
    way_rows = []
    for name, chain, tags in WAYS:
        refs = [node_ids[chain[0]]]
        for a, b in zip(chain[:-1], chain[1:]):
            pa, pb = JUNCTIONS[a], JUNCTIONS[b]
            n_seg = max(1, int(_haversine_m(pa, pb) / SPACING_M))
            for k in range(1, n_seg):
                t = k / n_seg
                lat = pa[0] + (pb[0] - pa[0]) * t
                lon = pa[1] + (pb[1] - pa[1]) * t
                shape_rows.append(
                    f'  <node id="{next_id}" lat="{lat:.7f}" '
                    f'lon="{lon:.7f}"/>\n')
                refs.append(next_id)
                next_id += 1
            refs.append(node_ids[b])
        rows = [f'  <way id="{way_id}">\n']
        rows += [f'    <nd ref="{r}"/>\n' for r in refs]
        rows.append(f'    <tag k="name" v="{name}"/>\n')
        for k, v in tags.items():
            rows.append(f'    <tag k="{k}" v="{v}"/>\n')
        rows.append('  </way>\n')
        way_rows.append("".join(rows))
        way_id += 1

    for row in shape_rows:
        w(row)
    for row in way_rows:
        w(row)

    # Boundary-clipped way: EDSA continues north out of the extract —
    # the second <nd> has no <node>, so parsers must drop the edge.
    w(f'  <way id="{way_id}">\n'
      f'    <nd ref="{node_ids["monumento"]}"/>\n'
      f'    <nd ref="990001"/>\n'
      f'    <tag k="name" v="Epifanio de los Santos Avenue"/>\n'
      f'    <tag k="highway" v="trunk"/>\n'
      f'  </way>\n')

    # Route relation (the EDSA Carousel busway): parsers ignore it.
    w('  <relation id="500000">\n'
      '    <member type="way" ref="100000" role=""/>\n'
      '    <tag k="type" v="route"/>\n'
      '    <tag k="route" v="bus"/>\n'
      '    <tag k="name" v="EDSA Carousel"/>\n'
      '  </relation>\n')
    w('</osm>\n')
    return out.getvalue()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    default_out = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "manila_arterials.osm.gz")
    ap.add_argument("--out", default=default_out)
    args = ap.parse_args()

    xml = build_xml()
    # mtime=0 + no embedded filename → deterministic gzip bytes
    # (re-runs reproduce the committed artifact wherever they write)
    with open(args.out, "wb") as raw:
        with gzip.GzipFile(filename="", fileobj=raw, mode="wb",
                           mtime=0) as gz:
            gz.write(xml.encode("utf-8"))
    n_nodes = xml.count("<node ")
    n_ways = xml.count("<way ")
    print(f"wrote {args.out}: {n_nodes} nodes, {n_ways} ways, "
          f"{os.path.getsize(args.out)} bytes gz")


if __name__ == "__main__":
    main()
