"""Chaos matrix: the serving fleet under injected faults, measured.

Chaos-engineering practice (Basiri et al., IEEE Software 2016): the
resilience claims of PR 1-3 — circuit breakers, retry, hedging,
write-behind journaling, netbus reconnect, deadline shedding — are only
real if they hold under injected failure. This harness boots the REAL
fleet (supervisor + worker processes + in-process gateway, the
``bench_fleet.py`` topology) per scenario, injects faults through the
``routest_tpu/chaos`` layer (worker-side via ``RTPU_CHAOS_*`` env,
gateway-side via an in-process engine) or actuates them directly
(broker SIGKILL, ``supervisor.kill_replica``), and records per scenario:
client error rate, p95 latency, shed (429) / expired (504) counts, and
scenario-specific invariants — most importantly ZERO lost writes after
the store-outage journal replay.

Scenarios: baseline, deadline_storm, slow_replica, replica_crash,
store_outage, device_error_burst, netbus_kill.

Writes ``artifacts/chaos_matrix.json``.

Usage: python scripts/bench_chaos.py [--quick] [--seed 7]
       [--scenarios name ...] [--out artifacts/chaos_matrix.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")

PREDICT_BODY = {"summary": {"distance": 8000}, "weather": "Sunny",
                "traffic": "Medium", "driver_age": 35,
                "pickup_time": "2026-07-29T18:00:00"}

ROUTE_BODY = {
    "source_point": {"lat": 14.5836, "lon": 121.0409},
    "destination_points": [
        {"lat": 14.5507, "lon": 121.0262, "payload": 1},
        {"lat": 14.5866, "lon": 121.0566, "payload": 1}],
    "driver_details": {"driver_name": "chaos", "vehicle_type": "car",
                       "vehicle_capacity": 100,
                       "maximum_distance": 300000, "driver_age": 31},
    "meta": {"origin_id": "o-chaos", "destination_ids": ["d1", "d2"]},
}

TRACKER_BODY = {
    "route_id": "chaos", "route": [[121.05, 14.55], [121.06, 14.56]],
    "destinations": [{"lat": 14.56, "lon": 121.06}],
    "driver_name": "chaos", "vehicle_type": "car",
    "duration": 600, "distance": 5000, "trips": 1,
    "pickup_time": "2026-07-29T18:00:00",
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(base, path, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read() or b"{}")
        except ValueError:
            return e.code, {}


def _get(base, path, timeout=15.0):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read() or b"{}")
        except ValueError:
            return e.code, {}


# ── fleet lifecycle ───────────────────────────────────────────────────

def boot_fleet(n: int, extra_env=None, **gw_cfg):
    """→ (supervisor, gateway, base_url). Real serving workers on the
    hermetic CPU backend behind an in-process gateway."""
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    ports = [_free_port() for _ in range(n)]
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        "ROUTEST_WARM_BUCKETS": "0",   # boot speed; warmed per replica
        "ROUTEST_MESH": "0",
        "ETA_MODEL_PATH": MODEL,
    })
    env.update(extra_env or {})
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    if not sup.ready(timeout=300):
        sup.drain(timeout=10)
        raise RuntimeError("fleet workers never became ready")
    for port in ports:  # warm the serving path (first XLA compile)
        _post(f"http://127.0.0.1:{port}", "/api/predict_eta", PREDICT_BODY)
    cfg = FleetConfig(**{"eject_after": 3, "cooldown_s": 1.0,
                         "max_inflight": 32, "queue_depth": 128, **gw_cfg})
    gw = Gateway([("127.0.0.1", p) for p in ports], cfg, supervisor=sup)
    httpd = gw.serve("127.0.0.1", 0)
    return sup, gw, f"http://127.0.0.1:{httpd.server_address[1]}"


def shutdown_fleet(sup, gw):
    try:
        gw.drain(timeout=5)
    finally:
        sup.drain(timeout=15)


# ── load + measurement ────────────────────────────────────────────────

def drive_load(base, n_requests, concurrency=4, path="/api/predict_eta",
               body=PREDICT_BODY, headers_fn=None, mid_hook=None):
    """Threaded load phase → (statuses dict, latencies list). ``mid_hook``
    fires once, halfway through, on the driver thread (fault actuation
    point). ``headers_fn(i)`` may add per-request headers."""
    statuses: dict = {}
    latencies: list = []
    lock = threading.Lock()
    counter = {"i": 0}

    def worker():
        while True:
            with lock:
                i = counter["i"]
                if i >= n_requests:
                    return
                counter["i"] += 1
            if mid_hook is not None and i == n_requests // 2:
                mid_hook()
            hdrs = headers_fn(i) if headers_fn else None
            t0 = time.perf_counter()
            try:
                status, _ = _post(base, path, body, headers=hdrs,
                                  timeout=30.0)
            except Exception:
                status = -1  # transport failure seen by the client
            dt = (time.perf_counter() - t0) * 1000.0
            with lock:
                statuses[status] = statuses.get(status, 0) + 1
                latencies.append(dt)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return statuses, sorted(latencies)


def _p95(latencies):
    if not latencies:
        return None
    return round(latencies[min(len(latencies) - 1,
                               int(0.95 * len(latencies)))], 2)


def _registry_total(base, names):
    """Sum the given counter families across all replicas' registries
    (via the gateway's ?replicas=1 passthrough)."""
    _, snap = _get(base, "/api/metrics?replicas=1", timeout=30.0)
    total = 0.0
    for rep in (snap.get("replica_metrics") or {}).values():
        reg = (rep or {}).get("registry") or {}
        for name in names:
            for series in (reg.get(name) or {}).get("series", ()):
                total += series.get("value", 0)
    return total


def summarize(statuses, latencies, gw):
    total = sum(statuses.values())
    errors = sum(c for s, c in statuses.items()
                 if s == -1 or (500 <= s and s != 504))
    return {
        "requests": total,
        "statuses": {str(k): v for k, v in sorted(statuses.items())},
        "error_rate": round(errors / total, 4) if total else None,
        "p95_ms": _p95(latencies),
        "shed_429": statuses.get(429, 0) + gw.shed_count,
        "expired_504": statuses.get(504, 0),
        "gateway": {"retries": gw.retries, "hedges": gw.hedges,
                    "hedge_wins": gw.hedge_wins, "shed": gw.shed_count},
    }


# ── scenarios ─────────────────────────────────────────────────────────

def scenario_baseline(args):
    sup, gw, base = boot_fleet(2)
    try:
        statuses, lat = drive_load(base, args.n, concurrency=4)
        out = summarize(statuses, lat, gw)
        out["description"] = "no faults; reference error rate and p95"
        out["pass"] = out["error_rate"] == 0.0
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_deadline_storm(args):
    """Every third request carries a 1 ms budget: it must be refused
    (504 at the replica edge / batcher, or 429 shed) and must NEVER
    reach device compute; normal requests keep serving."""
    sup, gw, base = boot_fleet(1)
    try:
        doomed = {"n": 0}

        def headers(i):
            if i % 3 == 0:
                doomed["n"] += 1
                return {"X-Deadline-Ms": "1"}
            return None

        statuses, lat = drive_load(base, args.n, concurrency=4,
                                   headers_fn=headers)
        out = summarize(statuses, lat, gw)
        out["doomed_requests"] = doomed["n"]
        out["replica_expired_total"] = _registry_total(
            base, ["rtpu_replica_expired_total",
                   "rtpu_batcher_expired_total"])
        out["description"] = ("1/3 of requests carry X-Deadline-Ms=1; "
                              "expired work is refused before device "
                              "compute")
        ok = statuses.get(200, 0)
        refused = statuses.get(504, 0) + statuses.get(429, 0) \
            + statuses.get(502, 0)
        out["pass"] = ok >= (args.n - doomed["n"]) * 0.95 \
            and refused >= doomed["n"] * 0.8
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_slow_replica(args):
    """One replica's hops injected with +300 ms latency (gateway-side
    chaos point gateway.forward.r1); hedging should keep the fleet p95
    well under the injected delay for most requests."""
    import routest_tpu.chaos as chaos

    sup, gw, base = boot_fleet(2, hedge=True, hedge_min_ms=30.0)
    chaos.configure(chaos.ChaosEngine(
        spec="gateway.forward.r1:latency=1.0/300", seed=args.seed))
    try:
        statuses, lat = drive_load(base, args.n, concurrency=4)
        out = summarize(statuses, lat, gw)
        out["injected_latency_ms"] = 300
        out["description"] = ("replica r1 +300 ms on every hop; hedging "
                              "races the healthy replica")
        out["pass"] = out["error_rate"] == 0.0
        return out
    finally:
        chaos.configure(None)
        shutdown_fleet(sup, gw)


def scenario_replica_crash(args):
    """SIGKILL one replica mid-load (the replica.kill fault point): the
    gateway's retry + breaker must absorb it with ~zero client errors;
    the supervisor restarts the worker."""
    sup, gw, base = boot_fleet(2)
    try:
        statuses, lat = drive_load(
            base, args.n, concurrency=4,
            mid_hook=lambda: sup.kill_replica(0))
        deadline = time.time() + 60
        while time.time() < deadline:
            snap = sup.snapshot()
            if snap["r0"]["alive"] and snap["r0"]["restarts"] >= 1:
                break
            time.sleep(0.5)
        out = summarize(statuses, lat, gw)
        out["restarts"] = sup.snapshot()["r0"]["restarts"]
        out["replica_recovered"] = sup.snapshot()["r0"]["alive"]
        out["description"] = ("SIGKILL r0 mid-load; retries absorb the "
                              "crash, supervisor restarts the worker")
        out["pass"] = out["error_rate"] <= 0.02 and out["replica_recovered"]
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_store_outage(args):
    """Worker-side chaos kills every store call until the injection
    budget (seeded, bounded) runs out: writes journal, reads fail fast
    with degraded markers, health-driven half-open probes recover the
    breaker, and the journal replays with ZERO lost writes."""
    n_routes = max(8, args.n // 6)
    sup, gw, base = boot_fleet(1, extra_env={
        "RTPU_CHAOS_SPEC": "store.http:error=1.0@20",
        "RTPU_CHAOS_SEED": str(args.seed),
        "RTPU_STORE_RETRIES": "1",
        "RTPU_STORE_BREAKER_AFTER": "2",
        "RTPU_STORE_COOLDOWN_S": "0.4",
    })
    stop_health = threading.Event()

    def health_poller():  # the orchestrator heartbeat that drives probes
        while not stop_health.is_set():
            _get(base, "/api/health", timeout=10.0)
            stop_health.wait(0.3)

    poller = threading.Thread(target=health_poller, daemon=True)
    poller.start()
    try:
        saved = degraded_writes = 0
        statuses: dict = {}
        latencies: list = []
        for _ in range(n_routes):
            t0 = time.perf_counter()
            status, body = _post(base, "/api/optimize_route", ROUTE_BODY)
            latencies.append((time.perf_counter() - t0) * 1000.0)
            statuses[status] = statuses.get(status, 0) + 1
            props = (body or {}).get("properties", {})
            if props.get("saved"):
                saved += 1
                if props.get("degraded"):
                    degraded_writes += 1
        # recovery + replay convergence
        rows, degraded_reads = [], 0
        deadline = time.time() + 90
        while time.time() < deadline:
            _, hist = _get(base, "/api/history?limit=100", timeout=30.0)
            if hist.get("degraded"):
                degraded_reads += 1
            rows = hist.get("items") or []
            if len(rows) >= saved and not hist.get("degraded"):
                break
            time.sleep(0.5)
        out = summarize(statuses, sorted(latencies), gw)
        out.update({
            "routes_saved": saved,
            "writes_journaled_degraded": degraded_writes,
            "degraded_reads_observed": degraded_reads,
            "history_rows_after_replay": len(rows),
            "lost_writes_after_replay": max(0, saved - len(rows)),
            "journal_replay_success": len(rows) >= saved,
            "description": ("every store call fails until the 20-fault "
                            "budget exhausts; journal replays on "
                            "recovery"),
        })
        out["pass"] = out["lost_writes_after_replay"] == 0 and saved > 0
        return out
    finally:
        stop_health.set()
        poller.join(timeout=5)
        shutdown_fleet(sup, gw)


def scenario_device_error_burst(args):
    """The device dies for a bounded burst (chaos device.compute): the
    affected requests surface 503 (never silent NaN), and the batcher
    keeps serving afterwards."""
    sup, gw, base = boot_fleet(1, extra_env={
        "RTPU_CHAOS_SPEC": "device.compute:error=0.3@10",
        "RTPU_CHAOS_SEED": str(args.seed),
    })
    try:
        statuses, lat = drive_load(base, args.n, concurrency=4)
        # After the burst budget: healthy again. Poll with patience —
        # the gateway breaker may still be cooling down, and the probe
        # traffic itself drains any injections the fail-fast breaker
        # kept unspent during the load phase.
        post_status = None
        deadline = time.time() + 45
        while time.time() < deadline:
            post_status, _ = _post(base, "/api/predict_eta", PREDICT_BODY)
            if post_status == 200:
                break
            time.sleep(0.5)
        out = summarize(statuses, lat, gw)
        out["healthy_after_burst"] = post_status == 200
        out["description"] = ("30% of device calls error for a 10-fault "
                              "burst; one fault fails its whole coalesced "
                              "batch loudly (5xx, never silent NaN) and "
                              "the gateway breaker fail-fasts while the "
                              "replica looks sick — then full recovery")
        out["pass"] = out["healthy_after_burst"]
        return out
    finally:
        shutdown_fleet(sup, gw)


def scenario_netbus_kill(args):
    """SIGKILL the SSE broker mid-stream, publish through the outage,
    restart it: the worker's reconnect + replay buffer and the
    subscription's resume must deliver every event."""
    broker_port = _free_port()

    def spawn_broker():
        proc = subprocess.Popen(
            [sys.executable, "-m", "routest_tpu.serve.netbus",
             "--port", str(broker_port)], cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", broker_port),
                                         timeout=0.2).close()
                return proc
            except OSError:
                time.sleep(0.1)
        raise RuntimeError("broker never listened")

    broker = spawn_broker()
    sup, gw, base = boot_fleet(1, extra_env={
        "REDIS_URL": f"tcp://127.0.0.1:{broker_port}",
        "RTPU_NETBUS_RECONNECT_S": "60",
    })
    n_events = max(6, args.n // 8)
    received: list = []

    def listen():
        req = urllib.request.Request(
            f"{base}/api/realtime_feed?channel=chaos"
            f"&max_events={2 * n_events}")
        try:
            with urllib.request.urlopen(req, timeout=180) as resp:
                for raw in resp:
                    line = raw.decode().strip()
                    if line.startswith("data: "):
                        received.append(json.loads(line[6:]))
                        if len(received) >= 2 * n_events:
                            return
        except Exception:
            return

    listener = threading.Thread(target=listen, daemon=True)
    listener.start()
    time.sleep(1.5)  # subscription registers at the broker
    try:
        published = 0
        for _ in range(n_events):  # phase 1: healthy
            status, _ = _post(base, "/api/update_tracker", TRACKER_BODY)
            published += status == 200
            time.sleep(0.05)
        broker.kill()
        broker.wait()
        time.sleep(0.3)
        for _ in range(n_events):  # phase 2: broker dead → buffered
            status, _ = _post(base, "/api/update_tracker", TRACKER_BODY)
            published += status == 200
            time.sleep(0.05)
        broker = spawn_broker()  # phase 3: recovery → replay
        deadline = time.time() + 60
        while len(received) < published and time.time() < deadline:
            time.sleep(0.5)
        out = {
            "events_published": published,
            "events_received": len(received),
            "events_lost": max(0, published - len(received)),
            "requests": 2 * n_events,
            "statuses": {"200": published},
            "error_rate": round(1.0 - published / (2 * n_events), 4),
            "p95_ms": None,
            "shed_429": 0,
            "expired_504": 0,
            "description": ("broker SIGKILLed mid-stream and restarted; "
                            "publish buffer + subscription resume "
                            "deliver every event"),
        }
        out["pass"] = out["events_lost"] == 0 and published == 2 * n_events
        return out
    finally:
        if broker.poll() is None:
            broker.kill()
        shutdown_fleet(sup, gw)


SCENARIOS = {
    "baseline": scenario_baseline,
    "deadline_storm": scenario_deadline_storm,
    "slow_replica": scenario_slow_replica,
    "replica_crash": scenario_replica_crash,
    "store_outage": scenario_store_outage,
    "device_error_burst": scenario_device_error_burst,
    "netbus_kill": scenario_netbus_kill,
}


def main() -> None:
    from routest_tpu.utils.logging import get_logger

    log = get_logger("routest_tpu.bench_chaos")
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="smaller load phases")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--scenarios", nargs="*", default=None,
                        choices=sorted(SCENARIOS))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "chaos_matrix.json"))
    args = parser.parse_args()
    args.n = 40 if args.quick else 120

    names = args.scenarios or list(SCENARIOS)
    results = {}
    for name in names:
        log.info("chaos_scenario_started", scenario=name)
        t0 = time.time()
        try:
            results[name] = SCENARIOS[name](args)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}",
                             "pass": False}
            log.error("chaos_scenario_failed", scenario=name,
                      error=f"{type(e).__name__}: {e}")
        results[name]["wall_s"] = round(time.time() - t0, 1)
        log.info("chaos_scenario_finished", scenario=name,
                 wall_s=results[name]["wall_s"],
                 ok=results[name].get("pass"))

    record = {
        "generated_unix": int(time.time()),
        "seed": args.seed,
        "load_per_scenario": args.n,
        "host": {"cpu_count": os.cpu_count(),
                 "platform": sys.platform},
        "note": ("1-core hosts time-share replicas: p95 under fault "
                 "measures degraded-mode behavior, not parallel "
                 "capacity (see fleet_scale.json)."),
        "scenarios": results,
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    log.info("chaos_matrix_written", path=args.out,
             scenarios=len(results),
             all_pass=all(r.get("pass") for r in results.values()))


if __name__ == "__main__":
    main()
