"""Incident-correlation bench: every page names its suspect change.

The ISSUE-20 acceptance bar for the change ledger + suspect ranker
(docs/OBSERVABILITY.md "Change ledger & incident correlation"): three
injected incidents, each flowing through the REAL pipeline — state
changes recorded into the process ChangeLedger, a page edge fired by
the real machinery, the flight recorder ranking suspects into the
bundle's ``suspects.json`` — with the injected cause ranked #1:

- ``bad_deploy`` — a broken version (stub worker serving 500s) rolled
  out through the canary state machine over a real multi-process stub
  fleet; the ``canary_error_rate`` rollback bundle must rank the
  rollout's own ``rollout.phase`` transition first, matched on the
  offending version, above the live-flip noise recorded beside it.
- ``jammed_customize`` — a chaos-jammed metric customize cycle
  (``live.customize:error=1.0``) driven through the real
  ``MetricCustomizer`` → a real ``SloEngine`` burn-rate page; the
  suspect must be the jam (``live.customize_failed`` / ``chaos.*``),
  never a legitimate pre-jam flip.
- ``region_kill`` — a geo-front ``kill_region`` over two stub regions;
  a reachability SLO pages naming the dead region, and ``region.kill``
  must rank first matched on the region label.

Plus ``clean_window``: ≥20 legitimate metric flips (real customize
cycles) and ≥2 verified model swaps (real ``EtaService`` golden-batch
gate) under a healthy ticking SLO engine — zero pages, zero false
attributions.

Each scenario installs a PRIVATE ledger + recorder (swap-and-restore,
same discipline as ``tests/test_ledger.py``), so the artifact shows
exactly the events that scenario produced.

Usage: python scripts/bench_incidents.py [--quick]
       [--scenarios bad_deploy jammed_customize region_kill
        clean_window]
       [--out artifacts/incidents.json]
"""

from __future__ import annotations

import argparse
import http.server
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ── stub workers (same harness as tests/test_rollout.py) ─────────────

_STUB_WORKER = """
import http.server, json, os
VERSION = os.environ.get("RTPU_VERSION") or None
FAIL = os.environ.get("STUB_FAIL") == "1"
class H(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def log_message(self, *a):
        pass
    def _send(self, code, payload):
        b = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(b)))
        self.end_headers()
        self.wfile.write(b)
    def do_GET(self):
        bare = self.path.split("?", 1)[0]
        if bare == "/api/health":
            self._send(200, {"checks": {"model": {
                "status": "ok", "generation": 1,
                "fingerprint": "stub-" + (VERSION or "none")}},
                "status": "ok"})
        elif bare == "/api/version":
            self._send(200, {"version_label": VERSION,
                             "build": {"version": "stub"},
                             "model": {"generation": 1,
                                       "fingerprint":
                                       "stub-" + (VERSION or "none")}})
        else:
            self._send(200, {"ok": True, "version": VERSION})
    def do_POST(self):
        n = int(self.headers.get("Content-Length") or 0)
        self.rfile.read(n)
        if FAIL:
            self._send(500, {"error": "stub failure", "version": VERSION})
        else:
            self._send(200, {"eta_minutes_ml": 1.0, "version": VERSION})
srv = http.server.ThreadingHTTPServer(("127.0.0.1", int(os.environ["PORT"])), H)
srv.daemon_threads = True
srv.serve_forever()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(base, path, payload, timeout=15.0):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


class _Pump:
    """Background client pumping the gateway so the canary comparison
    has traffic to judge."""

    def __init__(self, base, interval_s=0.005):
        self.base = base
        self.interval_s = interval_s
        self.statuses = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.is_set():
            try:
                status, _ = _post(self.base, "/api/predict_eta", {},
                                  timeout=10)
                self.statuses.append(status)
            except Exception:
                pass
            time.sleep(self.interval_s)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=10)


# ── per-scenario obs install (swap-and-restore) ──────────────────────

class _Obs:
    """A private ChangeLedger + FlightRecorder installed process-wide
    for one scenario, restored on exit."""

    def __init__(self, workdir: str, name: str) -> None:
        from routest_tpu.core.config import LedgerConfig, RecorderConfig
        from routest_tpu.obs.ledger import (ChangeLedger,
                                            configure_change_ledger)
        from routest_tpu.obs.recorder import (FlightRecorder,
                                              configure_recorder)
        from routest_tpu.obs.registry import MetricsRegistry

        self._configure_ledger = configure_change_ledger
        self._configure_recorder = configure_recorder
        self.dir = os.path.join(workdir, name)
        self.ledger = ChangeLedger(
            config=LedgerConfig(enabled=True, capacity=512,
                                window_s=900.0, max_suspects=5,
                                publish=False, channel="rtpu.changes",
                                incidents_kept=64, region=""),
            registry=MetricsRegistry())
        self.recorder = FlightRecorder(RecorderConfig(
            dir=self.dir, min_interval_s=0.0, followup_s=0.0))
        self.recorder.register_change_ledger(self.ledger)

    def __enter__(self):
        self._prev_ledger = self._configure_ledger(self.ledger)
        self._configure_recorder(self.recorder)
        return self

    def __exit__(self, *exc):
        self._configure_ledger(self._prev_ledger)
        self._configure_recorder(None)

    def incident(self, reason: str):
        """Newest incident with ``reason`` → (incident, suspects from
        the bundle's suspects.json) or (None, [])."""
        incs = [i for i in self.recorder.incidents_snapshot()
                if i.get("reason") == reason]
        if not incs:
            return None, []
        inc = incs[-1]
        path = os.path.join(self.dir, inc["bundle"], "suspects.json")
        try:
            with open(path) as f:
                return inc, json.load(f)["suspects"]
        except OSError:
            return inc, []


def _thin_suspects(suspects, n=3):
    return [{"kind": s["event"]["kind"], "score": s["score"],
             "matched": s["matched"], "mismatched": s["mismatched"],
             "age_s": s["age_s"],
             "labels": {k: s["event"][k]
                        for k in ("replica", "version", "region",
                                  "bucket") if s["event"].get(k)}}
            for s in suspects[:n]]


def _flip_noise(count: int) -> None:
    """Legitimate fleet-wide flips recorded beside the incident — the
    ranker must keep them below the true cause."""
    from routest_tpu.obs.ledger import record_change

    for i in range(count):
        record_change("live.flip", detail={"epoch": 1000 + i,
                                           "obs_edges": 12})


# ── a minimal real customize loop (jam + clean-window scenarios) ─────

class _TinyRouter:
    """The slice of the router surface MetricCustomizer touches:
    ``edge_time_s`` + ``install_live_metric``. The live.flip ledger
    record comes from the REAL customizer path; only the metric
    install is stubbed (the full path is proven in
    tests/test_live_traffic.py and bench_live_traffic.py)."""

    def __init__(self, n_edges: int = 16) -> None:
        import numpy as np

        self._base = np.full(n_edges, 5.0, dtype=np.float32)
        self.installs = 0

    def edge_time_s(self, hour):
        return self._base

    def install_live_metric(self, metric, epoch, route=True):
        self.installs += 1
        return {"epoch": epoch}


def _customizer():
    import numpy as np

    from routest_tpu.live.customize import MetricCustomizer
    from routest_tpu.live.state import CongestionState

    state = CongestionState(np.full(16, 5.0, dtype=np.float32),
                            half_life_s=30, stale_s=600)
    return MetricCustomizer(_TinyRouter(), state, interval_s=1,
                            min_obs_edges=0)


def _engine(target: float = 0.99):
    """A real SloEngine with tight windows so the bench ticks through
    a synthetic clock instead of sleeping."""
    from routest_tpu.core.config import SloConfig
    from routest_tpu.obs.registry import MetricsRegistry
    from routest_tpu.obs.slo import SloEngine

    return SloEngine(SloConfig(tick_s=1.0, fast_window_s=10.0,
                               slow_window_s=30.0, page_burn=2.0,
                               warn_burn=1.0), component="bench",
                     metrics_registry=MetricsRegistry())


# ── scenario: bad deploy via rollout ─────────────────────────────────

def scenario_bad_deploy(args, workdir: str) -> dict:
    """A version serving 500s canaries out through the real rollout
    state machine; the canary_error_rate rollback bundle must open
    with the rollout's own phase transition as suspect #1."""
    from routest_tpu.core.config import FleetConfig, RolloutConfig
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.rollout import RolloutController
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    with _Obs(workdir, "bad_deploy") as obs:
        ports = [_free_port() for _ in range(2)]
        sup = ReplicaSupervisor(
            ports, command=lambda p: [sys.executable, "-c", _STUB_WORKER],
            probe_interval_s=0.15, backoff_base_s=0.2, backoff_cap_s=1.0)
        sup.start()
        if not sup.ready(timeout=30):
            sup.drain(timeout=10)
            raise RuntimeError("stub fleet never became ready")
        gw = Gateway([("127.0.0.1", p) for p in ports],
                     FleetConfig(hedge=False), supervisor=sup)
        httpd = gw.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            _flip_noise(3)
            ctl = RolloutController(sup, gw, RolloutConfig(
                canary_fraction=0.25, canary_replicas=1, bake_s=30.0,
                tick_s=0.1, max_unavailable=1, min_canary_requests=5,
                max_error_rate=0.05, max_error_ratio=3.0,
                latency_threshold_ms=1500.0,
                max_latency_regression=0.25, crash_restarts=2,
                boot_timeout_s=20.0, health_timeout_s=5.0,
                drain_timeout_s=5.0))
            with _Pump(base, interval_s=0.002):
                assert ctl.start("v2-err", env={
                    "RTPU_VERSION": "v2-err", "STUB_FAIL": "1"})
                final = ctl.wait(timeout=90)
            inc, suspects = obs.incident("rollout_rollback")
            rollback = next((h for h in ctl.snapshot()["history"]
                             if h.get("event") == "rollback"), None)
            top = suspects[0] if suspects else None
            out = {
                "final_state": final,
                "rollback_trigger": (rollback or {}).get("trigger"),
                "ledger": obs.ledger.snapshot()["kinds"],
                "page_scope": (inc or {}).get("detail"),
                "suspects": _thin_suspects(suspects),
            }
            out["checks"] = {
                "rolled_back": final == "rolled_back",
                "paged_with_suspects": bool(inc and suspects),
                "true_cause_ranked_first": bool(
                    top and top["event"]["kind"] == "rollout.phase"),
                "offending_version_matched": bool(
                    top and top["event"].get("version") == "v2-err"
                    and "version" in top["matched"]),
                "noise_below_cause": bool(
                    top and top["event"]["kind"] != "live.flip"),
            }
            out["pass"] = all(out["checks"].values())
            return out
        finally:
            gw.drain(timeout=5)
            sup.drain(timeout=10)


# ── scenario: chaos-jammed customize cycle ───────────────────────────

def scenario_jammed_customize(args, workdir: str) -> dict:
    """Healthy customize cycles, then chaos jams the refresh point;
    the cycle-availability SLO burns into a real page whose bundle
    must blame the jam, not the legitimate flips before it."""
    from routest_tpu import chaos
    from routest_tpu.obs.slo import SloObjective

    with _Obs(workdir, "jammed_customize") as obs:
        cust = _customizer()
        cycles = {"total": 0, "bad": 0}
        engine = _engine()
        engine.add_objective(SloObjective(
            "availability:customize", "availability", 0.99,
            lambda: (cycles["total"], cycles["bad"]),
            detail={"surface": "live.customize"}))
        engine.on_page.append(obs.recorder.on_slo_page)
        now = 1000.0
        # Healthy window first: real flips, burn stays zero.
        for _ in range(args.clean_ticks):
            cycles["total"] += 1
            if not cust.run_once(now=now)["flipped"]:
                cycles["bad"] += 1
            engine.tick(now=now)
            now += 1.0
        flips_before = cust.flips
        paged_clean = bool(obs.recorder.incidents_snapshot())
        # Jam: every cycle now dies at the chaos point (recorded as
        # chaos.arm + chaos.fire + live.customize_failed).
        chaos.configure(chaos.ChaosEngine(
            spec="live.customize:error=1.0", seed=args.seed))
        try:
            ticks_to_page = None
            for i in range(60):
                cycles["total"] += 1
                if not cust.run_once(now=now)["flipped"]:
                    cycles["bad"] += 1
                engine.tick(now=now)
                now += 1.0
                if obs.recorder.incidents_snapshot():
                    ticks_to_page = i + 1
                    break
        finally:
            chaos.configure(None)
        inc, suspects = obs.incident("slo_page")
        top = suspects[0] if suspects else None
        jam_kinds = {"live.customize_failed", "chaos.fire", "chaos.arm"}
        out = {
            "clean_flips": flips_before,
            "ticks_to_page": ticks_to_page,
            "ledger": obs.ledger.snapshot()["kinds"],
            "page_scope": (inc or {}).get("detail"),
            "suspects": _thin_suspects(suspects),
        }
        out["checks"] = {
            "clean_window_quiet": not paged_clean and flips_before > 0,
            "paged_with_suspects": bool(inc and suspects),
            "true_cause_ranked_first": bool(
                top and top["event"]["kind"] in jam_kinds),
            "no_flip_blamed": bool(
                top and top["event"]["kind"] != "live.flip"),
        }
        out["pass"] = all(out["checks"].values())
        return out


# ── scenario: region kill at the geo-front ───────────────────────────

class _StubRegion:
    """One region as the front's health poll sees it: /up + /api/live."""

    def __init__(self) -> None:
        class H(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({"ok": True, "enabled": False}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.base = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def scenario_region_kill(args, workdir: str) -> dict:
    """kill_region("east") on a two-region geo-front; a reachability
    SLO pages naming the dead region and region.kill must rank first,
    matched on the region label, above fleet-wide flip noise."""
    from routest_tpu.obs.slo import SloObjective
    from routest_tpu.serve.fleet.geofront import GeoFront, RegionHandle

    with _Obs(workdir, "region_kill") as obs:
        east, west = _StubRegion(), _StubRegion()
        front = GeoFront([RegionHandle("east", east.base,
                                       kill=east.stop),
                          RegionHandle("west", west.base)])
        front.serve("127.0.0.1", 0)
        try:
            polls = {"total": 0, "bad": 0}

            def sample():
                regions = front.snapshot()["regions"]
                polls["total"] += len(regions)
                polls["bad"] += sum(1 for st in regions.values()
                                    if not st["up"])
                return polls["total"], polls["bad"]

            engine = _engine()
            engine.add_objective(SloObjective(
                "reachability:regions", "availability", 0.99, sample,
                detail={"surface": "geofront health"}))

            def page(name, detail):
                down = [n for n, st in
                        front.snapshot()["regions"].items()
                        if not st["up"]]
                obs.recorder.on_slo_page(name, {
                    **detail, "dead_region": ",".join(down) or None})

            engine.on_page.append(page)
            now = 1000.0
            for _ in range(args.clean_ticks):
                engine.tick(now=now)
                now += 1.0
            paged_clean = bool(obs.recorder.incidents_snapshot())
            _flip_noise(5)
            front.kill_region("east")
            ticks_to_page = None
            for i in range(60):
                engine.tick(now=now)
                now += 1.0
                if obs.recorder.incidents_snapshot():
                    ticks_to_page = i + 1
                    break
            inc, suspects = obs.incident("slo_page")
            top = suspects[0] if suspects else None
            out = {
                "ticks_to_page": ticks_to_page,
                "ledger": obs.ledger.snapshot()["kinds"],
                "page_scope": (inc or {}).get("detail"),
                "suspects": _thin_suspects(suspects),
            }
            out["checks"] = {
                "clean_window_quiet": not paged_clean,
                "paged_with_suspects": bool(inc and suspects),
                "dead_region_named": bool(
                    inc and (inc.get("detail") or {}).get("dead_region")
                    == "east"),
                "true_cause_ranked_first": bool(
                    top and top["event"]["kind"] == "region.kill"),
                "region_matched": bool(
                    top and top["event"].get("region") == "east"
                    and "region" in top["matched"]),
            }
            out["pass"] = all(out["checks"].values())
            return out
        finally:
            front.drain(timeout=5)
            west.stop()


# ── scenario: clean window — zero pages, zero false attributions ─────

def scenario_clean_window(args, workdir: str) -> dict:
    """≥20 legitimate metric flips (real customize cycles) and ≥2
    verified model swaps (real EtaService golden-batch gate) under a
    healthy ticking SLO engine: the ledger fills, nothing pages, and
    no incident attributes anything."""
    import jax

    from routest_tpu.core.config import ServeConfig
    from routest_tpu.core.dtypes import F32_POLICY
    from routest_tpu.models.eta_mlp import EtaMLP
    from routest_tpu.obs.slo import SloObjective
    from routest_tpu.serve.ml_service import EtaService
    from routest_tpu.train.checkpoint import save_model

    with _Obs(workdir, "clean_window") as obs:
        # Real verified swaps: each perturbed artifact passes the
        # golden-batch gate and records model.swap from the accept path.
        model = EtaMLP(hidden=(8,), policy=F32_POLICY)
        params = model.init(jax.random.PRNGKey(args.seed))
        path = os.path.join(workdir, "clean_model.msgpack")
        save_model(path, model, params)
        svc = EtaService(ServeConfig(), model_path=path)
        if not svc.available:
            raise RuntimeError("EtaService failed to load the model")
        swaps = 0
        for k in range(1, 3):
            close = jax.tree_util.tree_map(
                lambda x: x * (1.0 + 1e-4 * k), params)
            save_model(path, model, close)
            st = os.stat(path)
            os.utime(path, ns=(st.st_atime_ns,
                               st.st_mtime_ns + 1_000_000 * k))
            if svc.reload_if_changed():
                swaps += 1
        # Real flips under a healthy SLO tick.
        cust = _customizer()
        cycles = {"total": 0, "bad": 0}
        engine = _engine()
        engine.add_objective(SloObjective(
            "availability:customize", "availability", 0.99,
            lambda: (cycles["total"], cycles["bad"]),
            detail={"surface": "live.customize"}))
        engine.on_page.append(obs.recorder.on_slo_page)
        now = 1000.0
        for _ in range(max(args.clean_flips, 20)):
            cycles["total"] += 1
            if not cust.run_once(now=now)["flipped"]:
                cycles["bad"] += 1
            engine.tick(now=now)
            now += 1.0
        kinds = obs.ledger.snapshot()["kinds"]
        incidents = obs.recorder.incidents_snapshot()
        out = {
            "flips": kinds.get("live.flip", 0),
            "verified_swaps": kinds.get("model.swap", 0),
            "ledger": kinds,
            "incidents": len(incidents),
        }
        out["checks"] = {
            "enough_flips": out["flips"] >= 20,
            "enough_swaps": swaps >= 2
            and out["verified_swaps"] >= 2,
            "zero_pages": len(incidents) == 0,
            "zero_false_attributions": all(
                not i.get("suspects") for i in incidents),
        }
        out["pass"] = all(out["checks"].values())
        return out


SCENARIOS = {
    "bad_deploy": scenario_bad_deploy,
    "jammed_customize": scenario_jammed_customize,
    "region_kill": scenario_region_kill,
    "clean_window": scenario_clean_window,
}


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--scenarios", nargs="*", default=None,
                        choices=sorted(SCENARIOS))
    parser.add_argument("--cache-dir", default=os.path.join(
        REPO, "artifacts", "bench_cache", "incidents"))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "incidents.json"))
    args = parser.parse_args()
    args.clean_ticks = 8 if args.quick else 15
    args.clean_flips = 20 if args.quick else 30

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    os.makedirs(args.cache_dir, exist_ok=True)
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache(os.path.join(args.cache_dir, "xla"))
    from routest_tpu.utils.logging import get_logger

    log = get_logger("routest_tpu.bench_incidents")
    t0 = time.time()
    workdir = tempfile.mkdtemp(prefix="incidents-")
    results = {}
    try:
        plan = args.scenarios or list(SCENARIOS)
        for i, name in enumerate(plan):
            print(f"[{i + 1}/{len(plan)}] scenario {name}…", flush=True)
            t = time.perf_counter()
            try:
                results[name] = SCENARIOS[name](args, workdir)
            except Exception as e:
                results[name] = {"error": f"{type(e).__name__}: {e}",
                                 "pass": False}
                log.error("incidents_scenario_failed", scenario=name,
                          error=f"{type(e).__name__}: {e}")
            results[name]["wall_s"] = round(time.perf_counter() - t, 1)
            print(f"  {name}: "
                  f"{'PASS' if results[name].get('pass') else 'FAIL'} "
                  f"({results[name]['wall_s']}s)", flush=True)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    record = {
        "generated_unix": int(t0),
        "host": {"cpus": n_cpus, "platform": sys.platform},
        # Structural caveats (skip reasons are fields, never prose in a
        # note): attribution is a pure function of the ledger + page
        # scope, so the checks are host-independent; only wall-seconds
        # (rollout convergence, ticks-to-page) are time-shared numbers.
        "host_caveat": (
            f"cpu record on {n_cpus} core(s): rollout and page "
            "latencies are time-shared-host numbers; judge the "
            "structural checks (true cause ranked #1, matched labels, "
            "quiet clean window), which are host-independent"
            if n_cpus <= 2 else None),
        "skipped": None,
        "config": {"seed": args.seed, "quick": args.quick,
                   "clean_ticks": args.clean_ticks,
                   "clean_flips": args.clean_flips},
        "scenarios": results,
        "all_pass": all(r.get("pass") for r in results.values()),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
    log.info("incidents_written", path=args.out,
             all_pass=record["all_pass"])
    print(json.dumps(record, indent=2, default=str))
    if not record["all_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()
