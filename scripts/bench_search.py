"""Search-quality benchmark: how much better than reference-greedy?

Quantifies round 3's optimization-search upgrades against the
reference's only solver (greedy nearest-neighbor, ``Flaskr/utils.py:
111-139``) on two axes VERDICT.md asked for:

1. Tour cost on 20-stop multi-trip instances: greedy vs +2-opt vs
   +2-opt+cross-trip-relocate (the full ``refine=True`` pipeline).
2. Ranking hit-rate vs exhaustive on N ≤ 8: how often a fixed candidate
   budget contains the true optimum — uniform sampling (round 2's
   generator) vs perturbed-greedy (round 3's).

Writes artifacts/search_quality.json and prints a markdown table.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from routest_tpu.data import geo  # noqa: E402
from routest_tpu.optimize.ranking import (  # noqa: E402
    path_distances, perturbed_greedy_orders)
from routest_tpu.optimize.vrp import (  # noqa: E402
    greedy_vrp, refine_2opt, solve_host, tour_cost, trips_cost)


def _instance(rng, n):
    latlon = np.stack([
        14.4 + 0.3 * rng.random(n + 1),
        120.95 + 0.18 * rng.random(n + 1),
    ], axis=1).astype(np.float32)
    return np.asarray(geo.distance_matrix_m(jnp.asarray(latlon), 1.3))


def bench_tour_cost(n_instances=25, n_stops=20, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for _ in range(n_instances):
        dist = _instance(rng, n_stops)
        demands = rng.integers(1, 4, n_stops).astype(np.float32)
        cap = 12.0  # forces ~2-4 trips
        sol = greedy_vrp(jnp.asarray(dist), jnp.asarray(demands),
                         jnp.asarray(cap, jnp.float32),
                         jnp.asarray(1e12, jnp.float32))
        order_g, tid_g = np.asarray(sol.order), np.asarray(sol.trip_ids)
        cost_greedy = tour_cost(dist, order_g, tid_g)
        two = np.asarray(refine_2opt(jnp.asarray(dist), sol.order,
                                     sol.trip_ids))
        cost_2opt = tour_cost(dist, two, tid_g)
        full = solve_host(dist, demands, cap, 1e12, refine=True)
        cost_full = trips_cost(dist, full["trips"])
        rows.append((cost_greedy, cost_2opt, cost_full))
    arr = np.asarray(rows)
    greedy, twoopt, full = arr.mean(axis=0)
    return {
        "instances": n_instances,
        "n_stops": n_stops,
        "mean_cost_m": {"greedy": round(float(greedy), 1),
                        "greedy+2opt": round(float(twoopt), 1),
                        "greedy+2opt+relocate+swap+oropt23": round(float(full), 1)},
        "improvement_vs_greedy_pct": {
            "greedy+2opt": round(100 * (1 - twoopt / greedy), 2),
            "greedy+2opt+relocate+swap+oropt23": round(100 * (1 - full / greedy), 2)},
    }


def bench_ranking_hitrate(n_instances=40, n_stops=8, budget=64, seed=1):
    """Pr[candidate pool contains the optimal tour] at a fixed budget
    (8! = 40320 ≫ budget, so blind sampling almost never hits)."""
    rng = np.random.default_rng(seed)
    hits_uniform = hits_informed = 0
    regret_u = regret_i = 0.0
    for _ in range(n_instances):
        dist = _instance(rng, n_stops)
        best = min(
            _perm_len(dist, p)
            for p in itertools.permutations(range(n_stops)))
        uni = np.stack([rng.permutation(n_stops) for _ in range(budget)]
                       ).astype(np.int32)
        inf_orders = perturbed_greedy_orders(
            dist, budget, seed=int(rng.integers(1 << 30)))
        d_uni = float(np.asarray(path_distances(
            jnp.asarray(dist), jnp.asarray(uni))).min())
        d_inf = float(np.asarray(path_distances(
            jnp.asarray(dist), jnp.asarray(inf_orders))).min())
        hits_uniform += d_uni <= best + 1e-3
        hits_informed += d_inf <= best + 1e-3
        regret_u += d_uni / best - 1
        regret_i += d_inf / best - 1
    return {
        "instances": n_instances,
        "n_stops": n_stops,
        "budget": budget,
        "optimum_hit_rate": {
            "uniform": round(hits_uniform / n_instances, 3),
            "perturbed_greedy": round(hits_informed / n_instances, 3)},
        "mean_regret_pct": {
            "uniform": round(100 * regret_u / n_instances, 2),
            "perturbed_greedy": round(100 * regret_i / n_instances, 2)},
    }


def _perm_len(dist, perm):
    seq = [0] + [j + 1 for j in perm] + [0]
    return float(sum(dist[a, b] for a, b in zip(seq[:-1], seq[1:])))


def main():
    t0 = time.time()
    report = {
        "tour_cost_20_stops": bench_tour_cost(),
        "ranking_vs_exhaustive": bench_ranking_hitrate(),
        "seconds": None,
    }
    report["seconds"] = round(time.time() - t0, 1)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "search_quality.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)

    tc = report["tour_cost_20_stops"]
    rk = report["ranking_vs_exhaustive"]
    print("\n| solver (20 stops, multi-trip) | mean cost (m) | vs greedy |")
    print("|---|---|---|")
    for name in ("greedy", "greedy+2opt", "greedy+2opt+relocate+swap+oropt23"):
        imp = tc["improvement_vs_greedy_pct"].get(name, 0.0)
        print(f"| {name} | {tc['mean_cost_m'][name]:,} | "
              f"{'-' if name == 'greedy' else f'-{imp}%'} |")
    print(f"\n| candidate generator (N=8, budget {rk['budget']}) "
          f"| optimum hit rate | mean regret |")
    print("|---|---|---|")
    for name in ("uniform", "perturbed_greedy"):
        print(f"| {name} | {rk['optimum_hit_rate'][name]:.0%} | "
              f"{rk['mean_regret_pct'][name]}% |")
    print(f"\nwrote {out} ({report['seconds']}s)")


if __name__ == "__main__":
    main()
