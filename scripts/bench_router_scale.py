"""Road-router scale benchmark: metro-scale graphs (VERDICT r2 #5, r3 #1).

Measures the on-device shortest-path solver (``optimize/road_router.py``)
from the 2k-node serving default up to a ≥250k-node metro network with
OSM-extract topology — ORS-class territory, the engine the reference
outsources its matrix calls to (``Flaskr/utils.py:97-103``).

Two solver regimes are exercised: the flat batched Bellman-Ford below
``ROUTEST_HIER_MIN_NODES`` and the two-level partition overlay
(``optimize/hierarchy.py``) above it. Per size: graph build time,
router init (bridging + overlay precompute + device upload), cold solve
(XLA compile for that source bucket), warm solve wall time for a
16-waypoint batch (the quantity that gates request latency — one solve
prices a whole (M, M) leg matrix), the full matrix-operation time
(solve + M×M priced pairs incl. duration walks — the ORS matrix call
the reference rents), and with ``--verify`` a scipy Dijkstra oracle
parity check.

The ``--osm-nodes`` row builds an OSM-*topology* network (degree-2 bend
chains + one-ways via ``data/road_graph.py:subdivide_graph``), writes it
as real OSM XML and re-ingests it through ``data/osm.py:load_osm`` (the
native-scanner path), so the row routes what an actual extract parse
produces. A licensed real-city extract can't ship in this zero-egress
sandbox; topology + ingest path are the honest stand-in.

Writes artifacts/router_scale.json and prints a markdown table.
Runs on whatever jax backend is active (TPU through the tunnel when
available; --cpu forces the hermetic backend).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_solves(router, nodes):
    """One timing protocol for every regime: cold (pays compile) then
    min-of-3 warm. ``shortest`` host-syncs internally (device_get)."""
    t0 = time.perf_counter()
    dist, _ = router.shortest(nodes)
    t_cold = time.perf_counter() - t0
    solves = []
    for _ in range(3):
        t0 = time.perf_counter()
        dist, _ = router.shortest(nodes)
        solves.append(time.perf_counter() - t0)
    return dist, t_cold, min(solves)


def _bench_router(router, args, np, rng):
    pts = np.stack([
        rng.uniform(14.40, 14.68, args.waypoints),
        rng.uniform(120.96, 121.10, args.waypoints),
    ], axis=1).astype(np.float32)
    nodes = router.snap(pts)
    dist, t_cold, t_warm = _time_solves(router, nodes)
    phases = {}
    if router._hier is not None:
        # Per-phase breakdown (own dispatches — the fused program is
        # what t_warm measures): regressions localize to a phase.
        router._hier.timed_query(np.asarray(nodes, np.int32))
        _, phases = router._hier.timed_query(np.asarray(nodes, np.int32))
    # Full matrix operation (the ORS-comparable call the reference
    # rents per optimize request): solve + the M x M distance AND
    # duration matrices, exactly as /api/matrix serves them (durations
    # via the device-side pointer-doubling table, not per-pair walks).
    # Same min-of-3 protocol as the warm solve (fresh RoadLegs per
    # pass — memoization would make reused-object passes nearly free).
    matrix_times = []
    for _ in range(3):
        t0 = time.perf_counter()
        legs = router.route_legs(pts, 1.0, hour=8)
        legs.duration_matrix()
        matrix_times.append(time.perf_counter() - t0)
    return nodes, dist, t_cold, t_warm, min(matrix_times), phases


def _verify(router, nodes, dist, np):
    """Max relative error vs a float64 Dijkstra oracle (scipy)."""
    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra

    n = router.n_nodes
    adj = sp.coo_matrix(
        (router.length_m, (router.senders, router.receivers)),
        shape=(n, n)).tocsr()
    want = dijkstra(adj, directed=True, indices=np.asarray(nodes, np.int64))
    finite = np.isfinite(want)
    # Disagreement in EITHER direction is a failure: router-unreachable
    # where the oracle routes, or router-finite where the oracle says
    # unreachable (one-way pockets on the osm_extract row).
    if (dist[finite] > 1e37).any() or (dist[~finite] < 1e37).any():
        return float("inf")
    err = np.abs(dist[finite] - want[finite]) / np.maximum(want[finite], 1.0)
    return float(err.max())


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=int, nargs="+",
                        default=[2048, 8192, 50_000])
    parser.add_argument("--osm-nodes", type=int, default=250_000,
                        help="target size for the OSM-topology extract row "
                             "(0 skips it)")
    parser.add_argument("--osm-file", default="auto",
                        help="route a COMMITTED OSM extract as its own row "
                             "(topology=osm_file). Default 'auto' = the "
                             "curated Metro Manila arterial network "
                             "(artifacts/manila_arterials.osm.gz) when "
                             "present; 'none' skips; any path routes that "
                             "extract")
    parser.add_argument("--waypoints", type=int, default=16)
    parser.add_argument("--verify", action="store_true",
                        help="scipy Dijkstra oracle parity per row")
    parser.add_argument("--cpu", action="store_true",
                        help="hermetic CPU backend (TPU tunnel down)")
    parser.add_argument("--out", default=None,
                        help="artifact path (default artifacts/"
                             "router_scale.json); point one-off runs — "
                             "e.g. a country-scale probe — elsewhere so "
                             "the canonical record survives")
    parser.add_argument("--flat-compare", action="store_true",
                        help="for overlay rows, also time the flat "
                             "Bellman-Ford regime on the SAME graph, "
                             "waypoints and backend, recording "
                             "flat_warm_ms + overlay_speedup — the "
                             "apples-to-apples claim a cross-backend "
                             "comparison can't make")
    parser.add_argument("--flat-compare-max", type=int, default=50_000,
                        help="skip the flat comparison above this node "
                             "count (the diameter-bound sweep takes "
                             "minutes per solve there — the wall being "
                             "demonstrated)")
    parser.add_argument("--ml-compare", action="store_true",
                        help="for multi-level rows, also time a "
                             "SINGLE-level overlay on the same graph "
                             "(ROUTEST_HIER_MAX_LEVELS=1), recording "
                             "single_level_warm_ms + multi_level_speedup")
    parser.add_argument("--quick", action="store_true",
                        help="small preset for the slow-marked test: "
                             "one flat row, one overlay row with both "
                             "comparisons, no committed-extract row")
    args = parser.parse_args()
    # Solver bench: keep the route fastlane out of the matrix timings
    # (bench_router_serving.py measures the cache).
    os.environ.setdefault("ROUTEST_ROUTE_CACHE", "0")
    if args.quick:
        args.sizes = [2048, 24_000]
        args.osm_nodes = 0
        args.osm_file = "none"
        args.flat_compare = True
        args.ml_compare = True
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import numpy as np

    from routest_tpu.data.road_graph import generate_road_graph, subdivide_graph
    from routest_tpu.optimize.road_router import RoadRouter

    rows = []
    rng = np.random.default_rng(7)

    def _with_env(key, value, fn):
        old = os.environ.get(key)
        os.environ[key] = value
        try:
            return fn()
        finally:
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old

    def run_case(graph, t_gen, topology):
        t0 = time.perf_counter()
        router = RoadRouter(graph=graph, use_gnn=False, use_transformer=False)
        t_init = time.perf_counter() - t0
        nodes, dist, t_cold, t_warm, t_matrix, phases = _bench_router(
            router, args, np, rng)
        reach = float((dist < 1e37).mean())
        row = {
            "nodes": router.n_nodes,
            "edges": int(len(router.senders)),
            "topology": topology,
            "waypoints": args.waypoints,
            "graph_build_s": round(t_gen, 2),
            "router_init_s": round(t_init, 2),
            "solve_cold_ms": round(1000 * t_cold, 1),
            "solve_warm_ms": round(1000 * t_warm, 1),
            "matrix_warm_ms": round(1000 * t_matrix, 1),
            "reachable_frac": round(reach, 4),
            "query_phases_ms": phases,
            **router.solver_info,
        }
        if args.verify:
            row["oracle_max_rel_err"] = _verify(router, nodes, dist, np)
        if (args.flat_compare and row.get("solver") == "hierarchy"
                and router.n_nodes <= args.flat_compare_max):
            flat = _with_env("ROUTEST_HIER_MIN_NODES", "0",
                             lambda: RoadRouter(graph=graph, use_gnn=False,
                                                use_transformer=False))
            _, _, flat_warm = _time_solves(flat, nodes)  # same waypoints
            row["flat_warm_ms"] = round(1000 * flat_warm, 1)
            row["overlay_speedup"] = round(flat_warm / max(t_warm, 1e-9), 1)
            print(f"      flat_bf same graph/backend: warm "
                  f"{row['flat_warm_ms']}ms → overlay speedup "
                  f"{row['overlay_speedup']}x", flush=True)
        if (args.ml_compare and row.get("solver") == "hierarchy"
                and row.get("overlay", {}).get("n_levels", 1) > 1):
            # The baseline is the PR-8 regime: ONE level, no hub
            # labels — with labels enabled a single-level overlay
            # would get the top for free from the label fold, and the
            # comparison would no longer measure what stacking buys.
            single = _with_env(
                "ROUTEST_HIER_MAX_LEVELS", "1",
                lambda: _with_env(
                    "ROUTEST_HIER_LABELS", "0",
                    lambda: RoadRouter(graph=graph, use_gnn=False,
                                       use_transformer=False)))
            _, _, single_warm = _time_solves(single, nodes)
            row["single_level_warm_ms"] = round(1000 * single_warm, 1)
            row["multi_level_speedup"] = round(
                single_warm / max(t_warm, 1e-9), 2)
            print(f"      single-level same graph/backend: warm "
                  f"{row['single_level_warm_ms']}ms → multi-level "
                  f"speedup {row['multi_level_speedup']}x", flush=True)
        rows.append(row)
        print(f"  {row['nodes']:>7,} nodes {row['edges']:>9,} edges "
              f"[{topology}/{row['solver']}] | build {row['graph_build_s']}s "
              f"init {row['router_init_s']}s | solve cold "
              f"{row['solve_cold_ms']}ms warm {row['solve_warm_ms']}ms "
              f"matrix {row['matrix_warm_ms']}ms"
              + (f" | oracle err {row.get('oracle_max_rel_err'):.2e}"
                 if args.verify else ""), flush=True)

    for n in args.sizes:
        if n <= 0:          # `--sizes 0` = osm-extract row only
            continue
        t0 = time.perf_counter()
        graph = generate_road_graph(n_nodes=n, k=4, seed=0)
        run_case(graph, time.perf_counter() - t0, "generator")

    if args.osm_nodes:
        # intersections + 2 bends/street ≈ 1 + 2·2.43 nodes per
        # intersection for the k=4 kNN street graph
        n_int = max(1024, int(args.osm_nodes / 5.86))
        t0 = time.perf_counter()
        base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
        streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1,
                                  seed=0)
        from routest_tpu.data.osm import load_osm, save_osm

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "metro.osm.gz")
            save_osm(path, streets)
            extract = load_osm(path)
        run_case(extract, time.perf_counter() - t0, "osm_extract")

    osm_file = args.osm_file
    if osm_file == "auto":
        osm_file = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "artifacts",
            "manila_arterials.osm.gz")
        if not os.path.exists(osm_file):
            osm_file = "none"
    if osm_file != "none":
        # A real-provenance network (curated Metro Manila arterials,
        # scripts/make_manila_extract.py — VERDICT r4 next #6) beside
        # the generator rows: same solver, real street geometry.
        from routest_tpu.data.osm import load_osm as _load

        t0 = time.perf_counter()
        extract = _load(osm_file)
        run_case(extract, time.perf_counter() - t0, "osm_file")

    report = {"backend": jax.default_backend(), "rows": rows}
    out = args.out or os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "router_scale.json")
    if os.path.dirname(out):
        os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)

    print(f"\n| nodes | edges | topology | solver | warm solve "
          f"({args.waypoints} sources) | matrix ({args.waypoints}x"
          f"{args.waypoints}) | cold (compile) |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['nodes']:,} | {r['edges']:,} | {r['topology']} | "
              f"{r['solver']} | {r['solve_warm_ms']} ms | "
              f"{r['matrix_warm_ms']} ms | {r['solve_cold_ms']} ms |")
    print(f"\nbackend={report['backend']} → {out}")


if __name__ == "__main__":
    main()
