"""Road-router scale benchmark: metro-scale graphs (VERDICT r2 #5).

Measures the on-device batched Bellman-Ford shortest-path solver
(``optimize/road_router.py``) from the 2k-node serving default up to a
≥50k-node metro-scale network — ORS-class territory, the engine the
reference outsources its matrix calls to (``Flaskr/utils.py:97-103``).

Per size: graph build time, router init (bridging + device upload),
cold solve (includes the XLA compile for that padded source bucket),
and warm solve wall time for a 16-waypoint batch (the quantity that
gates request latency — one solve prices a whole (M, M) leg matrix).

Writes artifacts/router_scale.json and prints a markdown table.
Runs on whatever jax backend is active (TPU through the tunnel when
available; --cpu forces the hermetic backend).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--sizes", type=int, nargs="+",
                        default=[2048, 8192, 50_000])
    parser.add_argument("--waypoints", type=int, default=16)
    parser.add_argument("--cpu", action="store_true",
                        help="hermetic CPU backend (TPU tunnel down)")
    args = parser.parse_args()
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import numpy as np

    from routest_tpu.data.road_graph import generate_road_graph
    from routest_tpu.optimize.road_router import RoadRouter

    rows = []
    rng = np.random.default_rng(7)
    for n in args.sizes:
        t0 = time.perf_counter()
        graph = generate_road_graph(n_nodes=n, k=4, seed=0)
        t_gen = time.perf_counter() - t0

        t0 = time.perf_counter()
        router = RoadRouter(graph=graph, use_gnn=False)
        t_init = time.perf_counter() - t0

        pts = np.stack([
            rng.uniform(14.40, 14.68, args.waypoints),
            rng.uniform(120.96, 121.10, args.waypoints),
        ], axis=1).astype(np.float32)
        nodes = router.snap(pts)

        t0 = time.perf_counter()
        dist, _ = router.shortest(nodes)            # cold: pays compile
        t_cold = time.perf_counter() - t0

        solves = []
        for _ in range(3):                           # warm: steady state
            t0 = time.perf_counter()
            dist, _ = router.shortest(nodes)
            solves.append(time.perf_counter() - t0)
        t_warm = min(solves)

        reach = np.isfinite(
            np.where(dist < 1e37, dist, np.inf)).mean()
        row = {
            "nodes": router.n_nodes,
            "edges": int(len(router.senders)),
            "waypoints": args.waypoints,
            "graph_build_s": round(t_gen, 2),
            "router_init_s": round(t_init, 2),
            "solve_cold_ms": round(1000 * t_cold, 1),
            "solve_warm_ms": round(1000 * t_warm, 1),
            "max_iters_bound": router.max_iters,
            "reachable_frac": round(float(reach), 4),
        }
        rows.append(row)
        print(f"  {row['nodes']:>7,} nodes {row['edges']:>8,} edges | "
              f"build {row['graph_build_s']}s init {row['router_init_s']}s | "
              f"solve cold {row['solve_cold_ms']}ms warm "
              f"{row['solve_warm_ms']}ms", flush=True)

    report = {"backend": jax.default_backend(), "rows": rows}
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "router_scale.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)

    print(f"\n| nodes | edges | warm solve ({args.waypoints} sources) | "
          f"cold (compile) |")
    print("|---|---|---|---|")
    for r in rows:
        print(f"| {r['nodes']:,} | {r['edges']:,} | {r['solve_warm_ms']} ms "
              f"| {r['solve_cold_ms']} ms |")
    print(f"\nbackend={report['backend']} → {out}")


if __name__ == "__main__":
    main()
