"""Autoscale bench: SLO survival through a 10× flash crowd, measured.

The ISSUE-6 acceptance bar, end to end: a REAL fleet (supervisor +
serving worker processes + in-process gateway + autoscaler) is driven
by the open-loop generator (``routest_tpu/loadgen``) through a 10×
flash crowd and a compressed diurnal curve. The artifact must show

- the autoscaler scaling up during the spike and back down after,
- availability/latency SLOs out of ``page`` at the end of each
  scenario (or recovered within the fast window),
- a bounded shed rate (admission control degrades overload into 429s
  while the fleet grows — never a collapse),
- the same seed reproducing the same offered-load schedule, and
- a closed-loop vs open-loop comparison on the same overload exposing
  the coordinated-omission gap in recorded p99.

Rates are CALIBRATED, not hardcoded: a short closed-loop phase
measures one replica's capacity ``C`` on this host, then the flash
crowd offers ``C/8 → 10×`` (guaranteed overload at the spike on any
host) and the diurnal curve crests at ``1.2 C``. The artifact records
``C`` and the host shape; on a 1-core container extra replicas
time-share the core, so the scenario proves the CONTROL LOOP
(decisions, membership, drain, SLO state), not parallel speedup —
``host.note`` says so, same honesty contract as ``bench_fleet.py``.

Usage: python scripts/bench_autoscale.py [--quick] [--seed 42]
       [--scenarios flash_crowd diurnal closed_vs_open]
       [--out artifacts/autoscale.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get_json(base, path, timeout=15.0):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return json.loads(r.read())
    except Exception:
        return {}


def boot_fleet(args, autoscale: bool, cache_dir: str, recorder_dir: str,
               queue_depth: int = 32):
    """→ (supervisor, gateway, autoscaler-or-None, base_url). One real
    serving worker to start; the autoscaler grows it. Replicas share an
    XLA compile cache so scaled-up workers reuse the first boot's
    compilations (elastic boots must not pay full compile)."""
    from routest_tpu.core.config import (AutoscaleConfig, FleetConfig,
                                         RecorderConfig)
    from routest_tpu.obs.recorder import FlightRecorder, configure_recorder
    from routest_tpu.serve.fleet.autoscaler import Autoscaler
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    configure_recorder(FlightRecorder(RecorderConfig(
        dir=os.path.join(recorder_dir, "gateway"), min_interval_s=0.0)))
    # Cross-replica SSE needs the hermetic TCP broker (same wiring as
    # ``python -m routest_tpu.serve.fleet``): a tracker tick published
    # on a scaled-up replica must reach subscribers held on r0.
    from routest_tpu.serve.netbus import start_broker

    broker, _ = start_broker()
    env = dict(os.environ)
    env.update({
        "REDIS_URL": f"tcp://127.0.0.1:{broker.port}",
        "ROUTEST_FORCE_CPU": "1",
        "ROUTEST_MESH": "0",
        "ROUTEST_WARM_BUCKETS": "0",   # elastic boots: compile lazily …
        "RTPU_COMPILE_CACHE": cache_dir,   # … and share the XLA cache
        "ETA_MODEL_PATH": MODEL,
        "RTPU_RECORDER_DIR": os.path.join(recorder_dir, "workers"),
        "RTPU_RECORDER_MIN_INTERVAL_S": "0",
    })
    ports = [_free_port()]
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup._bench_broker = broker     # torn down in shutdown_fleet
    sup.start()
    if not sup.ready(timeout=300):
        sup.drain(timeout=10)
        broker.shutdown()
        raise RuntimeError("initial fleet worker never became ready")
    cfg = FleetConfig(hedge=False, eject_after=3, cooldown_s=1.0,
                      max_inflight=32, queue_depth=queue_depth)
    gw = Gateway([("127.0.0.1", p) for p in ports], cfg, supervisor=sup)
    httpd = gw.serve("127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    scaler = None
    if autoscale:
        # Constructed but NOT started: the calibration phase saturates
        # the 1-replica fleet on purpose, and a live controller would
        # (correctly!) scale against it — scenarios start the ticker
        # once the measured phase begins, so every decision in the
        # history is attributable to the offered scenario load.
        scaler = Autoscaler(sup, gw, AutoscaleConfig(
            enabled=True, min_replicas=1, max_replicas=args.max_replicas,
            tick_s=0.5, up_queue_frac=0.25, up_outstanding=8.0,
            up_burn=6.0, up_stable_ticks=2, up_step=1, up_cooldown_s=8.0,
            down_outstanding=1.0, down_stable_ticks=10, down_step=1,
            down_cooldown_s=10.0, startup_timeout_s=180.0,
            drain_timeout_s=10.0))
    return sup, gw, scaler, base


def shutdown_fleet(sup, gw, scaler):
    from routest_tpu.obs.recorder import configure_recorder

    try:
        if scaler is not None:
            scaler.stop()
        gw.drain(timeout=5)
    finally:
        sup.drain(timeout=20)
        broker = getattr(sup, "_bench_broker", None)
        if broker is not None:
            broker.shutdown()
        configure_recorder(None)


def warm(base: str, workload) -> None:
    from routest_tpu.loadgen import KeepAliveClient

    client = KeepAliveClient(base, timeout=120.0)
    try:
        for req in workload.sequence(4):
            client.send(req)
    finally:
        client.close()


def measure_capacity(base: str, workload, seconds: float) -> float:
    """Closed-loop ceiling of the current (1-replica) fleet in ok-rps —
    the calibration constant every scenario's rates derive from."""
    from routest_tpu.loadgen import run_closed_loop, summarize

    # 32 workers = the gateway's max_inflight: enough closed-loop
    # concurrency to actually saturate the replica (8 workers measured
    # the CLIENT's concurrency limit, ~40% under the true ceiling).
    records = run_closed_loop([base], workload.sequence(100_000),
                              workers=32, duration_s=seconds)
    rep = summarize(records, seconds, len(records), loop="closed")
    return max(5.0, rep["achieved_rps"])


class FleetWatcher:
    """Samples gateway fleet size + SLO state once a second while a
    scenario runs — the replica-count-vs-load timeline the acceptance
    criteria are judged on."""

    def __init__(self, gw) -> None:
        self.gw = gw
        self.samples = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        t0 = time.monotonic()
        while not self._stop.is_set():
            with self.gw._lock:
                live = sum(1 for r in self.gw.replicas if not r.draining)
                draining = sum(1 for r in self.gw.replicas if r.draining)
                queued = self.gw._waiters
                inflight = self.gw._inflight
            slo_state = "n/a"
            if self.gw.slo is not None:
                self.gw.slo.tick()
                slo_state = self.gw.slo.worst_state()
            pending = 0
            if self.gw.autoscaler is not None:
                with self.gw.autoscaler._lock:
                    pending = len(self.gw.autoscaler._pending)
            self.samples.append({
                "t": round(time.monotonic() - t0, 1),
                "replicas": live, "draining": draining,
                "pending": pending, "queued": queued,
                "inflight": inflight, "slo": slo_state,
            })
            self._stop.wait(1.0)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)

    def max_replicas(self) -> int:
        return max((s["replicas"] for s in self.samples), default=0)

    def slo_states(self) -> list:
        return [s["slo"] for s in self.samples]


def scenario_flash_crowd(args) -> dict:
    """Base → 10× spike → base, autoscaler on. Pure Zipf predict
    traffic so the PR-4 cache sees realistic key skew (hit-rate delta
    recorded from registry snapshots)."""
    from routest_tpu.loadgen import (RateCurve, ZipfODWorkload, cache_delta,
                                     fetch_metrics, poisson_schedule,
                                     run_open_loop, summarize, timeline)

    cache_dir = tempfile.mkdtemp(prefix="autoscale-xla-")
    recorder_dir = tempfile.mkdtemp(prefix="autoscale-pm-")
    sup, gw, scaler, base = boot_fleet(args, autoscale=True,
                                       cache_dir=cache_dir,
                                       recorder_dir=recorder_dir)
    try:
        workload = ZipfODWorkload(s=args.zipf_s, seed=args.seed)
        warm(base, workload)
        capacity = measure_capacity(base, workload, args.calibrate_s)
        time.sleep(1.0)          # calibration queue drains
        scaler.start()           # every decision now belongs to the run
        base_rate = max(2.0, capacity / 8.0)
        spike_rate = base_rate * 10.0          # ≈ 1.25 × capacity
        duration = args.baseline_s + args.spike_s + args.recovery_s
        curve = RateCurve.flash_crowd(base_rate, 10.0, args.baseline_s,
                                      args.spike_s)
        offsets = poisson_schedule(curve, duration, seed=args.seed)
        # Determinism receipt: the identical seed regenerates the
        # identical schedule (array-equal) and request sequence.
        offsets2 = poisson_schedule(curve, duration, seed=args.seed)
        reproducible = (len(offsets) == len(offsets2)
                        and bool((offsets == offsets2).all())
                        and workload.sequence(64)
                        == ZipfODWorkload(s=args.zipf_s,
                                          seed=args.seed).sequence(64))
        requests = workload.sequence(len(offsets))
        metrics_before = fetch_metrics(base, replicas=True)
        run_t0 = time.time()
        with FleetWatcher(gw) as watcher:
            records = run_open_loop([base], offsets, requests,
                                    workers=args.workers, timeout=35.0)
            # Keep watching (and keep the SLO engine ticking) until the
            # fleet is back to min size or the wait budget lapses — the
            # "and back down" half of the acceptance bar.
            settle_deadline = time.monotonic() + args.settle_s
            while time.monotonic() < settle_deadline:
                with gw._lock:
                    live = sum(1 for r in gw.replicas if not r.draining)
                pending = len(scaler._pending)
                if live <= 1 and pending == 0:
                    break
                time.sleep(1.0)
        metrics_after = fetch_metrics(base, replicas=True)
        report = summarize(records, duration, len(offsets))
        spike_lo, spike_hi = args.baseline_s, args.baseline_s + args.spike_s
        ups = [h for h in scaler.snapshot()["history"]
               if h.get("direction") == "up" and "phase" not in h]
        # Attribution: the decision must land in (or just after — the
        # hysteresis ticks) the spike window, not during baseline.
        ups_in_spike = [h for h in ups
                        if spike_lo <= h["t"] - run_t0 <= spike_hi + 10.0]
        downs = [h for h in scaler.snapshot()["history"]
                 if h.get("direction") == "down"
                 and h.get("phase") == "stopped"]
        joins = [h for h in scaler.snapshot()["history"]
                 if h.get("phase") == "joined"]
        slo_states = watcher.slo_states()
        final_fleet = gw.snapshot()["fleet"]
        out = {
            "capacity_rps_1_replica": round(capacity, 1),
            "offered": {"base_rps": round(base_rate, 1),
                        "spike_rps": round(spike_rate, 1),
                        "spike_window_s": [spike_lo, spike_hi],
                        "curve": curve.spec, "seed": args.seed,
                        "arrivals": len(offsets)},
            "schedule_reproducible": reproducible,
            "load": report,
            "load_timeline": timeline(records, bucket_s=2.0),
            "fleet_timeline": watcher.samples,
            "cache": cache_delta(metrics_before, metrics_after),
            "autoscale": {
                "up_decisions": len(ups),
                "up_decisions_in_spike_window": len(ups_in_spike),
                "down_decisions": len(downs),
                "joins": [{k: h[k] for k in ("replica", "boot_s")
                           if k in h} for h in joins],
                "max_replicas_seen": watcher.max_replicas(),
                "final_replicas": final_fleet["replica_count"],
                "history": scaler.snapshot()["history"],
            },
            "slo": {
                "states_seen": sorted(set(slo_states)),
                "final_state": slo_states[-1] if slo_states else "n/a",
                "paged": "page" in slo_states,
                "recovered": (slo_states[-1] != "page"
                              if slo_states else False),
            },
        }
        out["pass"] = bool(
            len(ups_in_spike) >= 1
            and watcher.max_replicas() >= 2
            and len(downs) >= 1
            and out["autoscale"]["final_replicas"] <= 1
            and report["error_rate"] <= args.max_error_rate
            and report["shed_rate"] <= args.max_shed_rate
            and out["slo"]["recovered"]
            and reproducible)
        return out
    finally:
        shutdown_fleet(sup, gw, scaler)
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(recorder_dir, ignore_errors=True)


def scenario_diurnal(args) -> dict:
    """One compressed day: mixed Zipf predict + history reads under a
    sinusoid cresting above one replica's capacity, with SSE
    subscribers held open across the whole curve. Pass = fleet size
    tracks the curve (up near the crest, back to min after the trough)
    with ~zero errors."""
    from routest_tpu.loadgen import (MixedWorkload, RateCurve, SseClients,
                                     poisson_schedule, run_open_loop,
                                     summarize, timeline)

    cache_dir = tempfile.mkdtemp(prefix="autoscale-xla-")
    recorder_dir = tempfile.mkdtemp(prefix="autoscale-pm-")
    sup, gw, scaler, base = boot_fleet(args, autoscale=True,
                                       cache_dir=cache_dir,
                                       recorder_dir=recorder_dir)
    try:
        workload = MixedWorkload(
            mix={"predict_eta": 0.87, "history": 0.08,
                 "update_tracker": 0.05},
            s=args.zipf_s, seed=args.seed)
        warm(base, workload.od)
        capacity = measure_capacity(base, workload.od, args.calibrate_s)
        time.sleep(1.0)
        scaler.start()
        period = args.diurnal_period_s
        curve = RateCurve.diurnal(base=max(1.0, capacity / 10.0),
                                  peak=capacity * 1.2, period_s=period,
                                  phase_s=0.0)   # trough at t=0
        duration = period + args.settle_s
        offsets = poisson_schedule(curve, period, seed=args.seed + 1)
        requests = workload.sequence(len(offsets))
        with FleetWatcher(gw) as watcher, \
                SseClients(base, n=2,
                           channel=workload.sse_channel) as sse:
            records = run_open_loop([base], offsets, requests,
                                    workers=args.workers, timeout=35.0)
            settle_deadline = time.monotonic() + args.settle_s
            while time.monotonic() < settle_deadline:
                with gw._lock:
                    live = sum(1 for r in gw.replicas if not r.draining)
                if live <= 1 and not scaler._pending:
                    break
                time.sleep(1.0)
            sse_snap = sse.snapshot()
        report = summarize(records, duration, len(offsets))
        hist = scaler.snapshot()["history"]
        ups = [h for h in hist
               if h.get("direction") == "up" and "phase" not in h]
        downs = [h for h in hist if h.get("phase") == "stopped"]
        out = {
            "capacity_rps_1_replica": round(capacity, 1),
            "offered": {"curve": curve.spec, "seed": args.seed + 1,
                        "arrivals": len(offsets)},
            "workload": workload.describe(),
            "sse": sse_snap,
            "load": report,
            "load_timeline": timeline(records, bucket_s=5.0),
            "fleet_timeline": watcher.samples,
            "autoscale": {"up_decisions": len(ups),
                          "down_decisions": len(downs),
                          "max_replicas_seen": watcher.max_replicas(),
                          "final_replicas":
                          gw.snapshot()["fleet"]["replica_count"],
                          "history": hist},
            "slo": {"final_state": watcher.slo_states()[-1]
                    if watcher.samples else "n/a"},
        }
        out["pass"] = bool(
            len(ups) >= 1
            and watcher.max_replicas() >= 2
            and out["autoscale"]["final_replicas"] <= 1
            and report["error_rate"] <= args.max_error_rate
            and out["slo"]["final_state"] != "page"
            and sse_snap["connected"] == sse_snap["requested"]
            and sse_snap["events"] > 0)
        return out
    finally:
        shutdown_fleet(sup, gw, scaler)
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(recorder_dir, ignore_errors=True)


def scenario_closed_vs_open(args) -> dict:
    """The coordinated-omission receipt: the SAME overloaded fixed
    1-replica fleet (autoscaler off), measured both ways. The
    closed-loop harness throttles itself to the server's pace, so its
    recorded p99 stays near the service time; the open-loop harness
    charges every request its wait from the INTENDED send and exposes
    the real user-visible tail."""
    from routest_tpu.loadgen import (RateCurve, ZipfODWorkload,
                                     paced_schedule, run_closed_loop,
                                     run_open_loop, summarize)

    cache_dir = tempfile.mkdtemp(prefix="autoscale-xla-")
    recorder_dir = tempfile.mkdtemp(prefix="autoscale-pm-")
    # Deep admission queue: THIS scenario wants the overload to QUEUE
    # (the backlog is what closed-loop accounting hides); the autoscale
    # scenarios keep the shallow production-shaped queue and shed.
    sup, gw, scaler, base = boot_fleet(args, autoscale=False,
                                       cache_dir=cache_dir,
                                       recorder_dir=recorder_dir,
                                       queue_depth=512)
    try:
        workload = ZipfODWorkload(s=args.zipf_s, seed=args.seed)
        warm(base, workload)
        capacity = measure_capacity(base, workload, args.calibrate_s)
        over_rate = capacity * 1.5
        dur = args.cvo_s
        # Deterministic pacing: identical offered schedule both runs.
        offsets = paced_schedule(RateCurve.constant(over_rate), dur)
        n = len(offsets)
        closed = summarize(
            run_closed_loop([base], workload.sequence(n), workers=8,
                            duration_s=dur, timeout=35.0),
            dur, n, loop="closed")
        time.sleep(2.0)   # let the queue fully drain between arms
        open_ = summarize(
            run_open_loop([base], offsets, workload.sequence(n),
                          workers=args.workers, timeout=35.0),
            dur, n)
        closed_p99 = (closed.get("latency") or {}).get("p99_ms")
        open_p99 = (open_.get("latency") or {}).get("p99_ms")
        gap = round(open_p99 / closed_p99, 2) \
            if closed_p99 and open_p99 else None
        return {
            "capacity_rps_1_replica": round(capacity, 1),
            "offered_rps": round(over_rate, 1),
            "duration_s": dur,
            "closed_loop": closed,
            "open_loop": open_,
            "coordinated_omission_p99_gap_x": gap,
            "explanation": (
                "identical server, identical offered schedule; the "
                "closed-loop arm self-throttles to the server's pace "
                "(its own achieved rps is the tell) so its p99 hides "
                "the backlog wait that open-loop accounting charges"),
            "pass": bool(gap is not None and gap >= args.min_co_gap),
        }
    finally:
        shutdown_fleet(sup, gw, scaler)
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(recorder_dir, ignore_errors=True)


SCENARIOS = {
    "flash_crowd": scenario_flash_crowd,
    "diurnal": scenario_diurnal,
    "closed_vs_open": scenario_closed_vs_open,
}


def main() -> None:
    from routest_tpu.utils.logging import get_logger

    log = get_logger("routest_tpu.bench_autoscale")
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--zipf-s", type=float, default=1.1)
    parser.add_argument("--workers", type=int, default=96,
                        help="open-loop sender threads")
    parser.add_argument("--max-replicas", type=int, default=3)
    parser.add_argument("--scenarios", nargs="*", default=None,
                        choices=sorted(SCENARIOS))
    parser.add_argument("--max-error-rate", type=float, default=0.01)
    parser.add_argument("--max-shed-rate", type=float, default=0.35,
                        help="shed(429) bound during the overload "
                             "scenarios — bounded load-shedding is the "
                             "design, collapse is the failure")
    parser.add_argument("--min-co-gap", type=float, default=2.0,
                        help="open-loop p99 must exceed closed-loop "
                             "p99 by at least this factor on the same "
                             "overload")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "autoscale.json"))
    args = parser.parse_args()
    if args.quick:
        args.calibrate_s = 3.0
        args.baseline_s, args.spike_s, args.recovery_s = 8.0, 20.0, 30.0
        args.settle_s = 90.0
        args.diurnal_period_s = 60.0
        args.cvo_s = 8.0
    else:
        args.calibrate_s = 5.0
        args.baseline_s, args.spike_s, args.recovery_s = 15.0, 30.0, 45.0
        args.settle_s = 150.0
        args.diurnal_period_s = 90.0
        args.cvo_s = 12.0

    results = {}
    for name in (args.scenarios or list(SCENARIOS)):
        log.info("autoscale_scenario_started", scenario=name)
        t0 = time.time()
        try:
            results[name] = SCENARIOS[name](args)
        except Exception as e:
            results[name] = {"error": f"{type(e).__name__}: {e}",
                             "pass": False}
            log.error("autoscale_scenario_failed", scenario=name,
                      error=f"{type(e).__name__}: {e}")
        results[name]["wall_s"] = round(time.time() - t0, 1)
        log.info("autoscale_scenario_finished", scenario=name,
                 ok=results[name].get("pass"),
                 wall_s=results[name]["wall_s"])

    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    record = {
        "generated_unix": int(time.time()),
        "host": {
            "cpu_count": cores,
            "multi_core": cores > 1,
            "note": None if cores > 1 else
            "1-core container: scaled-up replicas time-share the core, "
            "so these scenarios prove the control loop (decisions, "
            "membership changes, drains, SLO state) and bounded "
            "shedding — capacity relief from extra replicas binds on "
            "multi-core hosts",
        },
        "loadgen": {"zipf_s": args.zipf_s, "seed": args.seed,
                    "workers": args.workers,
                    "open_loop": "latency measured from intended send "
                                 "time (coordinated-omission-correct)"},
        "scenarios": results,
        "all_pass": all(r.get("pass") for r in results.values()),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    log.info("autoscale_written", path=args.out,
             all_pass=record["all_pass"])
    print(json.dumps({k: (v if k != "scenarios" else {
        n: {kk: vv for kk, vv in s.items()
            if kk in ("pass", "wall_s", "capacity_rps_1_replica",
                      "coordinated_omission_p99_gap_x", "autoscale",
                      "slo", "error")}
        for n, s in v.items()}) for k, v in record.items()}, indent=2))


if __name__ == "__main__":
    main()
