"""Metro routing as a SERVING workload → artifacts/router_serving.json.

The scale bench (``bench_osm_scale.py``) proves the solver; this one
proves the serving claim: a real fleet (supervisor + worker process +
gateway) pointed at a metro-scale OSM extract (``ROAD_GRAPH_OSM``)
answers ``/api/request_route`` with ``road_graph: true`` — street-
network shortest paths through the multi-level partition overlay —
under the open-loop load generator, with the SLO engine judging the
result. The workload's route traffic is Zipf-skewed over the OD
vocabulary (byte-stable bodies per pair), so the route fastlane and
the solve batcher are exercised the way production traffic would:
recorded alongside the CO-correct latency percentiles are the route-
cache hit rate and the batcher's merged-dispatch stats, read from the
worker's health provenance after the run.

``--compare-cache`` reruns the IDENTICAL offered load against a second
worker booted with ``ROUTEST_ROUTE_CACHE=0`` — same extract, same
overlay cache, same arrival schedule — so the artifact carries a
measured cache-on vs cache-off p95 on this host, not a claim.

The worker rehydrates the overlay from the shared
``ROUTEST_HIER_CACHE`` dir (this process builds it first) and reuses
this process's XLA compile cache, so replica boot measures cache-warm
fleet bring-up — the deployment path, not a cold lab build.

Usage: python scripts/bench_router_serving.py [--nodes 250000]
       [--rps 1.0] [--duration 90] [--quick] [--slo-ms 2500]
       [--compare-cache] [--out artifacts/router_serving.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_extract(n_nodes: int, out_dir: str) -> str:
    """Generate the OSM-topology metro extract (same recipe as the
    scale benches) and pre-build its overlay cache in-process."""
    from routest_tpu.data.osm import load_osm, save_osm
    from routest_tpu.data.road_graph import generate_road_graph, subdivide_graph
    from routest_tpu.optimize.road_router import RoadRouter

    n_int = max(1024, int(n_nodes / 5.86))
    base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
    streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1, seed=0)
    path = os.path.join(out_dir, f"metro_{n_nodes}.osm.gz")
    save_osm(path, streets)
    extract = load_osm(path)
    t0 = time.perf_counter()
    router = RoadRouter(graph=extract, use_gnn=False, use_transformer=False)
    print(f"  overlay prebuilt in {time.perf_counter() - t0:.1f}s "
          f"({router.n_nodes:,} nodes, "
          f"{router.solver_info.get('overlay', {}).get('n_levels')} levels, "
          f"hub_labels={router.solver_info.get('hub_labels')})",
          flush=True)
    return path


def run_phase(label: str, env: dict, workload, offsets, requests,
              slo_ms: float) -> dict:
    """Boot ONE worker + gateway under ``env``, warm it, replay the
    offered schedule, and return the phase record (load report, SLO
    states, worker health provenance)."""
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.loadgen import KeepAliveClient, run_open_loop, summarize
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    ports = [_free_port()]
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    gw = httpd = None
    try:
        if not sup.ready(timeout=600):
            raise RuntimeError(f"{label}: fleet worker never became ready")
        gw = Gateway([("127.0.0.1", p) for p in ports],
                     FleetConfig(hedge=False, max_inflight=32,
                                 queue_depth=64), supervisor=sup)
        httpd = gw.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"

        print(f"  [{label}] warming (first road request builds the "
              f"worker's router from cache)…", flush=True)
        client = KeepAliveClient(base, timeout=600.0)
        t0 = time.perf_counter()
        try:
            for req in workload.sequence(6):
                client.send(req)
        finally:
            client.close()
        warm_s = time.perf_counter() - t0

        duration = float(offsets[-1]) if len(offsets) else 0.0
        print(f"  [{label}] open loop: {len(offsets)} arrivals over "
              f"{duration:.0f}s…", flush=True)
        records = run_open_loop([base], offsets, requests, workers=16,
                                timeout=max(60.0, 4 * slo_ms / 1000))
        report = summarize(records, duration, len(offsets))

        gw.slo.tick()
        gateway_slo = gw.slo.snapshot()
        import urllib.request

        with urllib.request.urlopen(f"{base}/api/slo", timeout=30) as r:
            replica_slo = json.loads(r.read())
        health = json.loads(urllib.request.urlopen(
            f"{base}/api/health", timeout=30).read())
    finally:
        try:
            if httpd is not None:
                gw.drain(timeout=5)
        finally:
            sup.drain(timeout=20)

    road = (health.get("checks", {}).get("engine", {})
            .get("road_router")) or {}
    rr = report["routes"].get("/api/request_route", {})
    return {
        "label": label,
        "warm_first_requests_s": round(warm_s, 1),
        "load": report,
        "request_route_p95_ms": rr.get("latency", {}).get(
            "p95_ms", float("inf")),
        "slo": {"gateway_state": gateway_slo.get("state"),
                "replica_state": replica_slo.get("state"),
                "green": (gateway_slo.get("state") == "ok"
                          and replica_slo.get("state") == "ok")},
        "road_router": road,
        "route_cache": road.get("route_cache"),
        "batch": road.get("batch"),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=250_000)
    parser.add_argument("--rps", type=float, default=1.0,
                        help="offered open-loop arrival rate")
    parser.add_argument("--duration", type=float, default=90.0)
    parser.add_argument("--slo-ms", type=float, default=2500.0,
                        help="request_route latency SLO threshold "
                             "(registry bucket edges: 1000/2500/5000)")
    parser.add_argument("--quick", action="store_true",
                        help="50k extract, 45 s run — the slow-test "
                             "preset")
    parser.add_argument("--compare-cache", action="store_true",
                        help="rerun the identical offered load with the "
                             "route fastlane disabled and record both")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 50_000)
        args.duration = min(args.duration, 45.0)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.loadgen import MixedWorkload, RateCurve, poisson_schedule

    work_dir = tempfile.mkdtemp(prefix="router-serving-")
    hier_cache = os.path.join(work_dir, "hier")
    xla_cache = os.path.join(work_dir, "xla")
    os.environ["ROUTEST_HIER_CACHE"] = hier_cache
    # Postmortem bundles from warm-phase SLO edges (the first road
    # request pays the router build) belong to the run dir, not the
    # repo's artifacts/.
    os.environ["RTPU_RECORDER_DIR"] = os.path.join(work_dir, "postmortems")
    enable_compile_cache(xla_cache)
    slo_spec = (f"/api/request_route:latency_ms={args.slo_ms:.0f},"
                f"latency_target=0.95,availability=0.99;"
                f"/api/predict_eta:latency_ms=1000,latency_target=0.95,"
                f"availability=0.999")
    os.environ["RTPU_SLO_OBJECTIVES"] = slo_spec

    print(f"[1/3] building {args.nodes:,}-node extract + overlay cache…",
          flush=True)
    extract = build_extract(args.nodes, work_dir)

    env = dict(os.environ)
    env.update({
        "ROAD_GRAPH_OSM": extract,
        "ROUTEST_HIER_CACHE": hier_cache,
        "RTPU_COMPILE_CACHE": xla_cache,
        "ROUTEST_MESH": "0",
        "ROUTEST_WARM_BUCKETS": "0",
        "ETA_MODEL_PATH": MODEL,
        "RTPU_SLO_OBJECTIVES": slo_spec,
        # Route bodies are 3 waypoints (bucket 4); matrix/bench traffic
        # pads to 16; the batcher merges up to 32 rows.
        "ROUTEST_ROUTER_AOT": "2,4,16,32",
    })

    workload = MixedWorkload(
        mix={"request_route": 0.7, "predict_eta": 0.3},
        seed=args.seed, road_graph=True)
    curve = RateCurve.constant(args.rps)
    offsets = poisson_schedule(curve, args.duration, seed=args.seed)
    requests = workload.sequence(len(offsets))

    print("[2/3] fastlane-on phase (fleet: 1 worker + gateway)…",
          flush=True)
    phase_on = run_phase("cache-on", env, workload, offsets, requests,
                         args.slo_ms)

    phase_off = None
    if args.compare_cache:
        print("[3/3] fastlane-off phase (same offered load, "
              "ROUTEST_ROUTE_CACHE=0)…", flush=True)
        env_off = dict(env)
        env_off["ROUTEST_ROUTE_CACHE"] = "0"
        phase_off = run_phase("cache-off", env_off, workload, offsets,
                              requests, args.slo_ms)
    else:
        print("[3/3] skipped (--compare-cache off)", flush=True)

    p95_ms = phase_on["request_route_p95_ms"]
    slo_green = phase_on["slo"]["green"]
    passed = (p95_ms <= args.slo_ms and slo_green
              and phase_on["load"]["error_rate"] <= 0.01)
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    cache_stats = phase_on.get("route_cache") or {}
    record = {
        "host": {"cpus": n_cpus,
                 "note": "1 worker; wall latency scales with cores"},
        "host_caveat": f"cpu-backend record on {n_cpus} core(s): compare "
                       f"cache-on/off and batching ratios, not wall ms",
        "extract_nodes": args.nodes,
        "workload": workload.describe(),
        "offered": {"rps": args.rps, "duration_s": args.duration,
                    "arrivals": len(offsets)},
        "slo_threshold_ms": args.slo_ms,
        "warm_first_requests_s": phase_on["warm_first_requests_s"],
        "load": phase_on["load"],
        "request_route_p95_ms": p95_ms,
        "slo": phase_on["slo"],
        "road_router": phase_on["road_router"],
        "route_cache": cache_stats,
        "batch": phase_on.get("batch"),
        "pass": passed,
    }
    if phase_off is not None:
        off_p95 = phase_off["request_route_p95_ms"]
        record["cache_off"] = {
            "request_route_p95_ms": off_p95,
            "warm_first_requests_s": phase_off["warm_first_requests_s"],
            "load": phase_off["load"],
            "slo": phase_off["slo"],
            "route_cache": phase_off.get("route_cache"),
        }
        record["cache_speedup_p95"] = (
            round(off_p95 / p95_ms, 3)
            if p95_ms and p95_ms == p95_ms else None)

        def _mean(phase):
            return (phase["load"]["routes"]
                    .get("/api/request_route", {})
                    .get("latency", {}).get("mean_ms"))

        # At light offered load p95 is set by the occasional slow MISS
        # in either phase; the MEAN is the statistically meaningful
        # cache signal there (hits answer in ms, so the mean drops by
        # roughly the hit rate × miss cost).
        mean_on, mean_off = _mean(phase_on), _mean(phase_off)
        record["cache_speedup_mean"] = (
            round(mean_off / mean_on, 3)
            if mean_on and mean_off else None)
    out = args.out or os.path.join(REPO, "artifacts", "router_serving.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    msg = (f"\nrequest_route p95 {p95_ms} ms (SLO {args.slo_ms:.0f} ms) | "
           f"cache hit rate {cache_stats.get('hit_rate')} | "
           f"slo gateway={record['slo']['gateway_state']} "
           f"replica={record['slo']['replica_state']} | "
           f"errors {phase_on['load']['error_rate']:.2%}")
    if phase_off is not None:
        off_p95 = record["cache_off"]["request_route_p95_ms"]
        msg += (f" | cache-off p95 {off_p95} ms "
                f"({record['cache_speedup_p95']}x)")
    print(msg + f" → {out}")
    sys.exit(0 if passed else 1)


if __name__ == "__main__":
    main()
