"""Metro routing as a SERVING workload → artifacts/router_serving.json.

The scale bench (``bench_osm_scale.py``) proves the solver; this one
proves the serving claim: a real fleet (supervisor + worker process +
gateway) pointed at a metro-scale OSM extract (``ROAD_GRAPH_OSM``)
answers ``/api/request_route`` with ``road_graph: true`` — street-
network shortest paths through the multi-level partition overlay —
under the open-loop load generator, with the SLO engine judging the
result. Recorded: per-route CO-correct latency percentiles, the
configured SLO latency threshold, and both tiers' SLO states; the run
passes iff request_route p95 is inside the threshold and no SLO
objective pages.

The worker rehydrates the overlay from the shared
``ROUTEST_HIER_CACHE`` dir (this process builds it first) and reuses
this process's XLA compile cache, so replica boot measures cache-warm
fleet bring-up — the deployment path, not a cold lab build.

Usage: python scripts/bench_router_serving.py [--nodes 250000]
       [--rps 1.0] [--duration 90] [--quick] [--slo-ms 2500]
       [--out artifacts/router_serving.json]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def build_extract(n_nodes: int, out_dir: str) -> str:
    """Generate the OSM-topology metro extract (same recipe as the
    scale benches) and pre-build its overlay cache in-process."""
    from routest_tpu.data.osm import load_osm, save_osm
    from routest_tpu.data.road_graph import generate_road_graph, subdivide_graph
    from routest_tpu.optimize.road_router import RoadRouter

    n_int = max(1024, int(n_nodes / 5.86))
    base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
    streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1, seed=0)
    path = os.path.join(out_dir, f"metro_{n_nodes}.osm.gz")
    save_osm(path, streets)
    extract = load_osm(path)
    t0 = time.perf_counter()
    router = RoadRouter(graph=extract, use_gnn=False, use_transformer=False)
    print(f"  overlay prebuilt in {time.perf_counter() - t0:.1f}s "
          f"({router.n_nodes:,} nodes, "
          f"{router.solver_info.get('overlay', {}).get('n_levels')} levels)",
          flush=True)
    return path


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=250_000)
    parser.add_argument("--rps", type=float, default=1.0,
                        help="offered open-loop arrival rate")
    parser.add_argument("--duration", type=float, default=90.0)
    parser.add_argument("--slo-ms", type=float, default=2500.0,
                        help="request_route latency SLO threshold "
                             "(registry bucket edges: 1000/2500/5000)")
    parser.add_argument("--quick", action="store_true",
                        help="50k extract, 45 s run — the slow-test "
                             "preset")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 50_000)
        args.duration = min(args.duration, 45.0)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.loadgen import (MixedWorkload, RateCurve,
                                     KeepAliveClient, poisson_schedule,
                                     run_open_loop, summarize)
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    work_dir = tempfile.mkdtemp(prefix="router-serving-")
    hier_cache = os.path.join(work_dir, "hier")
    xla_cache = os.path.join(work_dir, "xla")
    os.environ["ROUTEST_HIER_CACHE"] = hier_cache
    # Postmortem bundles from warm-phase SLO edges (the first road
    # request pays the router build) belong to the run dir, not the
    # repo's artifacts/.
    os.environ["RTPU_RECORDER_DIR"] = os.path.join(work_dir, "postmortems")
    enable_compile_cache(xla_cache)
    slo_spec = (f"/api/request_route:latency_ms={args.slo_ms:.0f},"
                f"latency_target=0.95,availability=0.99;"
                f"/api/predict_eta:latency_ms=1000,latency_target=0.95,"
                f"availability=0.999")
    os.environ["RTPU_SLO_OBJECTIVES"] = slo_spec

    print(f"[1/4] building {args.nodes:,}-node extract + overlay cache…",
          flush=True)
    extract = build_extract(args.nodes, work_dir)

    print("[2/4] booting fleet (1 worker + gateway)…", flush=True)
    env = dict(os.environ)
    env.update({
        "ROAD_GRAPH_OSM": extract,
        "ROUTEST_HIER_CACHE": hier_cache,
        "RTPU_COMPILE_CACHE": xla_cache,
        "ROUTEST_MESH": "0",
        "ROUTEST_WARM_BUCKETS": "0",
        "ETA_MODEL_PATH": MODEL,
        "RTPU_SLO_OBJECTIVES": slo_spec,
    })
    ports = [_free_port()]
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    gw = httpd = None
    try:
        if not sup.ready(timeout=600):
            raise RuntimeError("fleet worker never became ready")
        gw = Gateway([("127.0.0.1", p) for p in ports],
                     FleetConfig(hedge=False, max_inflight=32,
                                 queue_depth=64), supervisor=sup)
        httpd = gw.serve("127.0.0.1", 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"

        workload = MixedWorkload(
            mix={"request_route": 0.7, "predict_eta": 0.3},
            seed=args.seed, road_graph=True)
        print("[3/4] warming (first road request builds the worker's "
              "router from cache)…", flush=True)
        client = KeepAliveClient(base, timeout=600.0)
        t0 = time.perf_counter()
        try:
            for req in workload.sequence(6):
                client.send(req)
        finally:
            client.close()
        warm_s = time.perf_counter() - t0

        print(f"[4/4] open loop: {args.rps} rps × {args.duration:.0f}s…",
              flush=True)
        curve = RateCurve.constant(args.rps)
        offsets = poisson_schedule(curve, args.duration, seed=args.seed)
        requests = workload.sequence(len(offsets))
        records = run_open_loop([base], offsets, requests, workers=16,
                                timeout=max(60.0, 4 * args.slo_ms / 1000))
        report = summarize(records, args.duration, len(offsets))

        # SLO judgement, both tiers: the gateway engine in this
        # process, the replica's via its API.
        gw.slo.tick()
        gateway_slo = gw.slo.snapshot()
        import urllib.request

        with urllib.request.urlopen(f"{base}/api/slo", timeout=30) as r:
            replica_slo = json.loads(r.read())
        health = json.loads(urllib.request.urlopen(
            f"{base}/api/health", timeout=30).read())
    finally:
        try:
            if httpd is not None:
                gw.drain(timeout=5)
        finally:
            sup.drain(timeout=20)

    rr = report["routes"].get("/api/request_route", {})
    p95_ms = rr.get("latency", {}).get("p95_ms", float("inf"))
    slo_green = (gateway_slo.get("state") == "ok"
                 and replica_slo.get("state") == "ok")
    passed = (p95_ms <= args.slo_ms and slo_green
              and report["error_rate"] <= 0.01)
    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    record = {
        "host": {"cpus": n_cpus,
                 "note": "1 worker; wall latency scales with cores"},
        "extract_nodes": args.nodes,
        "workload": workload.describe(),
        "warm_first_requests_s": round(warm_s, 1),
        "slo_threshold_ms": args.slo_ms,
        "load": report,
        "request_route_p95_ms": p95_ms,
        "slo": {"gateway_state": gateway_slo.get("state"),
                "replica_state": replica_slo.get("state"),
                "green": slo_green},
        "road_router": (health.get("checks", {}).get("engine", {})
                        .get("road_router")),
        "pass": passed,
    }
    out = args.out or os.path.join(REPO, "artifacts", "router_serving.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"\nrequest_route p95 {p95_ms} ms (SLO {args.slo_ms:.0f} ms) | "
          f"slo gateway={record['slo']['gateway_state']} "
          f"replica={record['slo']['replica_state']} | "
          f"errors {report['error_rate']:.2%} → {out}")
    sys.exit(0 if passed else 1)


if __name__ == "__main__":
    main()
