"""Export the serving ETA model as a self-contained StableHLO artifact.

Reads a msgpack params artifact (``save_model``), AOT-exports the
forward with a symbolic batch dimension, and writes a file the serving
layer can run WITHOUT this package's model code — point
``ETA_MODEL_PATH`` at it and ``EtaService`` serves it (kernel
``stablehlo_aot``). See ``train/checkpoint.export_serving_fn``.

Usage: python scripts/export_model.py [--model artifacts/eta_mlp.msgpack]
       [--out artifacts/eta_forward.stablehlo] [--platforms cpu,tpu] [--cpu]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default=None,
                        help="msgpack artifact (default: the serving "
                             "resolution — ETA_MODEL_PATH or the in-repo "
                             "artifact)")
    parser.add_argument("--out", default=None,
                        help="output path (default: <model>.stablehlo)")
    parser.add_argument("--platforms", default="cpu,tpu")
    parser.add_argument("--cpu", action="store_true")
    args = parser.parse_args()
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from routest_tpu.train.checkpoint import (default_model_path,
                                              export_serving_fn,
                                              load_exported_serving_fn,
                                              load_model)

    model_path = args.model or default_model_path()
    out = args.out or os.path.splitext(model_path)[0] + ".stablehlo"
    platforms = tuple(p.strip() for p in args.platforms.split(",") if p.strip())

    model, params = load_model(model_path)
    print(f"export: {model_path} (hidden={list(model.hidden)}, "
          f"quantiles={list(model.quantiles)}) → {out} "
          f"platforms={list(platforms)}")
    export_serving_fn(out, model, params, platforms=platforms)

    # Verify before declaring success: reload and compare one batch —
    # unless this machine cannot execute any target platform (e.g.
    # exporting a TPU-only artifact from a CPU box): the artifact is
    # still valid, it just can't be verified here.
    import numpy as np

    from routest_tpu.train.checkpoint import backend_platforms

    if not any(p in platforms for p in backend_platforms()):
        print(f"written: {os.path.getsize(out)} bytes. Backend "
              f"{backend_platforms()[0]} cannot execute platforms "
              f"{list(platforms)} — verification skipped; verify on a "
              f"target machine.")
        return

    from routest_tpu.data.features import batch_from_mapping
    from routest_tpu.data.synthetic import generate_dataset

    exported = load_exported_serving_fn(out)
    x = batch_from_mapping(generate_dataset(64, seed=9))
    forward = model.apply_quantiles if model.quantiles else model.apply
    want = np.asarray(forward(params, x))
    got = np.asarray(exported(x))
    # bf16-trunk models tolerate bf16-scale differences: the exported
    # program and the live jit may pick different (equally valid) dot
    # lowerings for the emulated-bf16 CPU path.
    import jax.numpy as jnp

    tight = model.policy.compute_dtype == jnp.float32
    np.testing.assert_allclose(got, want, rtol=2e-5 if tight else 2e-2,
                               atol=1e-4 if tight else 0.25)
    print(f"verified: {os.path.getsize(out)} bytes, parity on 64 rows OK "
          f"(max rel err {np.max(np.abs(got - want) / np.maximum(want, 1e-6)):.2e})")


if __name__ == "__main__":
    main()
