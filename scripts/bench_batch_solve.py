"""Many-query device batching curve → artifacts/batch_solve.json.

The router's solve program is batched over the source axis by
construction; this bench pins down what that is worth: K concurrent
point queries merged into ONE device dispatch versus K scalar
dispatches of the same program (the pre-batcher serving behavior), at
oracle parity. Two measurements per K:

- ``merged``: one ``_solve_rows`` call with K sources (what the
  ``_SolveBatcher`` dispatches after coalescing K concurrent
  ``request_route`` solves);
- ``scalar``: K sequential 1-source calls (each padded to the bucket-1
  program — the old per-request cost).

Plus a threaded section driving K worker threads of 1-source
``shortest()`` calls through the live batcher, recording the merged
occupancy actually achieved (the natural-batching regime: arrivals
during an in-flight solve drain as the next merged dispatch).

Usage: python scripts/bench_batch_solve.py [--nodes 250000] [--quick]
       [--no-verify] [--out artifacts/batch_solve.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=250_000)
    parser.add_argument("--quick", action="store_true",
                        help="50k extract — the slow-test preset")
    parser.add_argument("--ks", type=int, nargs="+",
                        default=[1, 2, 4, 8, 16, 32])
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--no-verify", action="store_true")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 50_000)

    if os.environ.get("ROUTEST_FORCE_CPU", "1") == "1":
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import numpy as np

    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache()
    from routest_tpu.data.road_graph import (generate_road_graph,
                                             subdivide_graph)
    from routest_tpu.optimize.road_router import RoadRouter

    n_int = max(1024, int(args.nodes / 5.86))
    base = generate_road_graph(n_nodes=n_int, k=4, seed=0)
    streets = subdivide_graph(base, bends_per_edge=2, oneway_frac=0.1,
                              seed=0)
    print(f"[1/3] building router ({args.nodes:,} requested nodes)…",
          flush=True)
    t0 = time.perf_counter()
    router = RoadRouter(graph=streets, use_gnn=False, use_transformer=False)
    build_s = time.perf_counter() - t0

    rng = np.random.default_rng(11)
    k_max = max(args.ks)
    sources = router.snap(np.stack([
        rng.uniform(14.40, 14.68, k_max),
        rng.uniform(120.96, 121.10, k_max)], axis=1).astype(np.float32))

    print("[2/3] K ladder (merged one-dispatch vs scalar dispatches)…",
          flush=True)
    rows = []
    for k in args.ks:
        sub = sources[:k]
        router._solve_rows(sub)                    # warm the bucket
        merged = []
        for _ in range(3):
            t0 = time.perf_counter()
            dist, _ = router._solve_rows(sub)
            merged.append(time.perf_counter() - t0)
        router._solve_rows(sub[:1])
        scalar = []
        for _ in range(3):
            t0 = time.perf_counter()
            for i in range(k):
                router._solve_rows(sub[i:i + 1])
            scalar.append(time.perf_counter() - t0)
        row = {
            "k": k,
            "merged_ms": round(1000 * min(merged), 2),
            "scalar_ms": round(1000 * min(scalar), 2),
            "merged_solves_per_s": round(k / min(merged), 2),
            "scalar_solves_per_s": round(k / min(scalar), 2),
            "speedup": round(min(scalar) / min(merged), 3),
        }
        if not args.no_verify:
            import scipy.sparse as sp
            from scipy.sparse.csgraph import dijkstra

            adj = sp.coo_matrix(
                (router.length_m, (router.senders, router.receivers)),
                shape=(router.n_nodes, router.n_nodes)).tocsr()
            want = dijkstra(adj, directed=True,
                            indices=np.asarray(sub, np.int64))
            dist, _ = router._solve_rows(sub)
            finite = np.isfinite(want)
            bad = (dist[finite] > 1e37).any() or (dist[~finite] < 1e37).any()
            err = float((np.abs(dist[finite] - want[finite])
                         / np.maximum(want[finite], 1.0)).max()) \
                if not bad else float("inf")
            row["oracle_max_rel_err"] = err
        rows.append(row)
        print(f"  K={k:>3}: merged {row['merged_ms']}ms "
              f"({row['merged_solves_per_s']}/s) vs scalar "
              f"{row['scalar_ms']}ms — {row['speedup']}x"
              + (f" | oracle {row.get('oracle_max_rel_err'):.1e}"
                 if "oracle_max_rel_err" in row else ""), flush=True)

    print(f"[3/3] {args.threads} threads through the live batcher…",
          flush=True)
    n_per_thread = 6
    barrier = threading.Barrier(args.threads)
    errors: list = []

    def worker(tid: int) -> None:
        try:
            barrier.wait(timeout=60)
            for i in range(n_per_thread):
                router.shortest(sources[(tid + i) % k_max:
                                        (tid + i) % k_max + 1])
        except BaseException as e:  # recorded below — the bench must fail
            errors.append(repr(e))

    router.shortest(sources[:1])                   # warm bucket 1
    before = router._solve_batcher.stats()
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(args.threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall = time.perf_counter() - t0
    after = router._solve_batcher.stats()
    total = args.threads * n_per_thread
    threaded = {
        "threads": args.threads,
        "solves": total,
        "wall_s": round(wall, 3),
        "solves_per_s": round(total / wall, 2),
        "dispatches": after["dispatches"] - before["dispatches"],
        "merged_requests": (after["merged_requests"]
                            - before["merged_requests"]),
        "max_occupancy": after["max_occupancy"],
        "errors": errors,
    }
    print(f"  {total} solves in {wall:.2f}s over "
          f"{threaded['dispatches']} dispatches "
          f"(max occupancy {threaded['max_occupancy']})", flush=True)

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    big = [r for r in rows if r["k"] >= 8]
    # Direction gate: merged dispatches must clearly beat scalar once
    # K amortizes (≥1.5× somewhere past K=8 and never degenerate),
    # at oracle parity on every row. The exact ratio per K moves with
    # bucket boundaries — the ≥1.2 floor catches a real regression,
    # not bucket noise.
    passed = (all(r.get("oracle_max_rel_err", 0.0) <= 1e-5 for r in rows)
              and bool(big) and max(r["speedup"] for r in big) >= 1.5
              and all(r["speedup"] >= 1.2 for r in big)
              and not errors)
    report = {
        "backend": jax.default_backend(),
        "host": {"cpus": n_cpus},
        "host_caveat": (None if jax.default_backend() == "tpu" else
                        f"cpu-backend record on {n_cpus} core(s): compare "
                        f"the K-scaling ratios, not wall ms"),
        "nodes": int(router.n_nodes),
        "edges": int(len(router.senders)),
        "router_build_s": round(build_s, 2),
        "solver": router.solver_info.get("solver"),
        "rows": rows,
        "threaded": threaded,
        "pass": passed,
    }
    out = args.out or os.path.join(REPO, "artifacts", "batch_solve.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"\nbatched-solve curve → {out} (pass={passed})")
    sys.exit(0 if passed else 1)


if __name__ == "__main__":
    main()
