"""Config-4 workload: road-graph GNN training over the full network.

Trains the edge-sharded RoadGNN on the synthetic Metro Manila road graph
and reports edge-time RMSE against two baselines:

- naive physics (length / speed limit + fixed overhead) — what a router
  would use with no learning;
- the noise floor (observed vs ground-truth time) — the best achievable.

Usage: python scripts/train_gnn.py [--nodes 2048] [--steps 400] [--quick]

The default --nodes 2048 matches the serving router's graph, so the
saved artifact's fingerprint lets the GNN go live on the request path;
other sizes (and --quick) train for experimentation and are not saved
to the serving path unless --save is given explicitly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


HELD_OUT_HOURS = (7, 12, 17)  # labels never seen in training


def main() -> None:
    parser = argparse.ArgumentParser()
    # default 2048 = the serving router's graph (road_router.RoadRouter),
    # so the saved artifact's fingerprint matches and the GNN goes live
    # on the request path.
    parser.add_argument("--nodes", type=int, default=2048)
    parser.add_argument("--steps", type=int, default=400)
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--osm", default=None, metavar="PATH",
                        help="train on an OSM XML extract (data/osm.py) "
                             "instead of the synthetic generator; targets "
                             "come from the congestion overlay "
                             "(road_graph.add_congestion_observations) and "
                             "the artifact fingerprint matches the router "
                             "serving that extract (ROAD_GRAPH_OSM)")
    parser.add_argument("--save", default=None,
                        help="artifact path (default: ROAD_GNN_PATH or "
                             "artifacts/road_gnn.msgpack — the same "
                             "resolution the serving router uses)")
    parser.add_argument("--no-save", action="store_true")
    parser.add_argument("--samples", type=int, default=1,
                        help="observations per edge from the congestion "
                             "overlay (add_congestion_observations "
                             "samples_per_edge). Each copy draws its own "
                             "hour, so >1 exposes the congestion curve's "
                             "shape at more points per edge — the "
                             "held-out-hours gap closer (ratio 3.07x -> "
                             "1.32x at 800-node scale going 1 -> 3). "
                             "OSM extracts should use >= 3")
    parser.add_argument("--report-out", default=None, metavar="PATH",
                        help="report artifact path (default: artifacts/"
                             "gnn_report_osm.json for --osm runs, else "
                             "gnn_report.json). Name it for one-off "
                             "extracts so the canonical reports survive")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--cpu", action="store_true",
                        help="hermetic 8-virtual-device CPU mesh (use when "
                             "the TPU tunnel is unavailable)")
    args = parser.parse_args()
    if args.report_out:
        # Resolve (and create) the report directory NOW: a bare filename
        # has an empty dirname (makedirs("") raises), and an unwritable
        # path must fail here, before hours of training, not after.
        args.report_out = os.path.abspath(args.report_out)
        report_dir = os.path.dirname(args.report_out)
        if report_dir:
            os.makedirs(report_dir, exist_ok=True)
    if args.quick:
        args.nodes, args.steps = 512, 120
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        # JAX_PLATFORMS env is re-exported by the axon site hook; only
        # the config API reliably selects the CPU backend.
        jax.config.update("jax_platforms", "cpu")

    import jax
    import numpy as np
    import optax

    from routest_tpu.core.mesh import MeshRuntime
    from routest_tpu.data.road_graph import (add_congestion_observations,
                                             generate_road_graph)
    from routest_tpu.models.gnn import RoadGNN, graph_batch

    runtime = MeshRuntime.create()
    # BOTH paths train on the EXACT routable graph a server aggregates
    # over — RoadRouter's post-component-bridging edge set — so the
    # artifact's fingerprint always passes the serving router's
    # compatibility check (a disconnected kNN draw or OSM extract gains
    # bridge edges; training on the raw arrays would fingerprint-mismatch
    # forever). Targets come from the congestion overlay.
    from routest_tpu.optimize.road_router import RoadRouter

    if args.osm:
        from routest_tpu.data.osm import load_osm

        router = RoadRouter(graph=load_osm(args.osm), use_gnn=False)
        args.nodes = router.n_nodes
        print(f"[1/3] OSM graph {args.osm}: {router.n_nodes} nodes, "
              f"mesh {dict(runtime.mesh.shape)}")
    else:
        print(f"[1/3] graph: {args.nodes} nodes, "
              f"mesh {dict(runtime.mesh.shape)}")
        router = RoadRouter(
            graph=generate_road_graph(n_nodes=args.nodes, k=4, seed=0),
            use_gnn=False)
    serving_graph = router.graph_dict()  # un-tiled: carries the fingerprint
    graph = add_congestion_observations(serving_graph, seed=0,
                                        samples_per_edge=args.samples)
    n_edges = len(graph["senders"])

    naive = graph["length_m"] / np.maximum(graph["speed_limit"], 0.1) + 4.0
    naive_rmse = float(np.sqrt(np.mean((naive - graph["time_s"]) ** 2)))
    floor_rmse = float(np.sqrt(np.mean(
        (graph["time_true_s"] - graph["time_s"]) ** 2)))
    # The held-out HOURS are rush/noon: congestion multiplies edge
    # times there, so the multiplicative observation noise has a larger
    # absolute sigma than the all-hours average. The honest yardstick
    # for the held-hours RMSE is the floor measured AT those hours —
    # judging it against the global floor overstates the model gap
    # (VERDICT r4 weak #5 did exactly that: 1.32x global was 1.10x
    # hours-specific after the --samples fix).
    _hh = np.isin(graph["hour"], HELD_OUT_HOURS)
    floor_hours_rmse = float(np.sqrt(np.mean(
        (graph["time_true_s"][_hh] - graph["time_s"][_hh]) ** 2)))
    print(f"      {n_edges} edges | naive-physics RMSE {naive_rmse:.2f}s | "
          f"noise floor {floor_rmse:.2f}s")

    model = RoadGNN(n_nodes=args.nodes, hidden=args.hidden, n_rounds=2)
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optax.adamw(optax.cosine_decay_schedule(3e-3, args.steps), 1e-4)
    opt_state = optimizer.init(params)
    step = model.make_sharded_train_step(runtime.mesh, optimizer)
    batch = graph_batch(graph, pad_to=runtime.n_data)
    coords = graph["node_coords"]

    # Two held-out regimes (edges still carry messages — it's their *time
    # labels* that are unseen by the loss):
    # 1. 10% random edges at seen hours — standard generalization;
    # 2. ALL edges sampled at HELD_OUT_HOURS — the non-circular test: the
    #    hour features are cyclical (Fourier), so the model must learn
    #    the congestion curve's shape to predict hours whose labels it
    #    never saw, rather than memorizing per-hour offsets from the
    #    generator it was trained on.
    rng = np.random.default_rng(1)
    eval_mask = np.zeros(len(batch.weights), bool)
    eval_idx = rng.choice(n_edges, size=max(1, n_edges // 10), replace=False)
    eval_mask[eval_idx] = True
    hour_mask = np.zeros(len(batch.weights), bool)
    hour_mask[:n_edges] = _hh
    train_weights = np.asarray(batch.weights) * ~(eval_mask | hour_mask)
    batch = batch._replace(weights=jax.numpy.asarray(train_weights))

    print(f"[2/3] training {args.steps} steps (edge-sharded over "
          f"{runtime.n_data} devices)")
    t0 = time.time()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, coords, batch)
        if (i + 1) % max(1, args.steps // 5) == 0:
            print(f"      step {i + 1}/{args.steps} mse={float(loss):.2f}")
    train_s = time.time() - t0

    pred = np.asarray(model.apply(params, coords, batch))[:n_edges]

    def _rmse(mask):
        return float(np.sqrt(np.mean((pred[mask] - graph["time_s"][mask]) ** 2)))

    def _naive_rmse(mask):
        return float(np.sqrt(np.mean((naive[mask] - graph["time_s"][mask]) ** 2)))

    held = eval_mask[:n_edges] & ~hour_mask[:n_edges]
    held_hours = hour_mask[:n_edges]
    # Yardstick symmetry: each RMSE is compared to the noise floor
    # measured over ITS OWN observation set — the random-held split
    # excludes the high-sigma rush/noon hours, so dividing it by the
    # global floor would claim "better than achievable".
    floor_held_rmse = float(np.sqrt(np.mean(
        (graph["time_true_s"][held] - graph["time_s"][held]) ** 2)))
    rmse = _rmse(held)
    naive_rmse = _naive_rmse(held)
    rmse_hours = _rmse(held_hours)
    naive_rmse_hours = _naive_rmse(held_hours)
    print(f"[3/3] GNN held-out RMSE {rmse:.2f}s (naive {naive_rmse:.2f}s, "
          f"floor {floor_rmse:.2f}s) | held-out HOURS {HELD_OUT_HOURS}: "
          f"GNN {rmse_hours:.2f}s vs naive {naive_rmse_hours:.2f}s | "
          f"{train_s:.1f}s")

    report = {
        "nodes": args.nodes,
        "edges": n_edges,
        "steps": args.steps,
        "samples_per_edge": args.samples,
        "gnn_rmse_s": rmse,
        "naive_rmse_s": naive_rmse,
        "held_out_hours": list(HELD_OUT_HOURS),
        "gnn_rmse_held_hours_s": rmse_hours,
        "naive_rmse_held_hours_s": naive_rmse_hours,
        "noise_floor_rmse_s": floor_rmse,
        "noise_floor_held_rmse_s": floor_held_rmse,
        "noise_floor_held_hours_rmse_s": floor_hours_rmse,
        "vs_floor_held": rmse / floor_held_rmse,
        "vs_floor_held_hours": rmse_hours / floor_hours_rmse,
        "train_seconds": train_s,
        "beats_naive": bool(rmse < naive_rmse
                            and rmse_hours < naive_rmse_hours),
    }
    if args.osm:
        report["osm"] = args.osm
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # --osm runs report separately: gnn_report.json is the config-4
    # (full synthetic network) benchmark artifact the driver reads.
    out = args.report_out or os.path.join(
        repo, "artifacts",
        "gnn_report_osm.json" if args.osm else "gnn_report.json")
    out_dir = os.path.dirname(out)
    if out_dir:  # bare filename ⇒ cwd; makedirs("") would raise
        os.makedirs(out_dir, exist_ok=True)
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
    print(f"      report → {out}")

    # Save gates: (a) quality — a failed run must never replace a good
    # model on the request path; (b) compatibility — the DEFAULT serving
    # path only accepts the serving router's graph size, so a --quick or
    # custom --nodes experiment can't overwrite the live artifact with a
    # fingerprint the router would refuse (silent free-flow degradation).
    # --osm runs must name their artifact explicitly (--save): the
    # DEFAULT path belongs to the synthetic serving graph, and an OSM
    # artifact silently clobbering it would free-flow-degrade a synthetic
    # server on its next boot (the fingerprint check refuses with only a
    # debug log).
    serving_compatible = (args.osm is None and args.nodes == 2048
                          and not args.quick)
    if not args.no_save and report["beats_naive"] and (
            args.save or serving_compatible):
        from routest_tpu.train.checkpoint import default_gnn_path, save_gnn

        artifact = args.save or default_gnn_path()
        # fingerprint from the UN-tiled serving graph, not the training
        # view (identical today; add_congestion_observations may tile)
        save_gnn(artifact, model, params, serving_graph)
        print(f"      artifact → {artifact}")
    elif not args.no_save and not report["beats_naive"]:
        print("      artifact NOT saved: run did not beat the naive baseline")
    elif not args.no_save:
        reason = ("--osm runs need an explicit --save PATH (point "
                  "ROAD_GNN_PATH at it when serving)" if args.osm
                  else "non-serving graph size (pass --save PATH to keep it)")
        print(f"      artifact NOT saved: {reason}")
    sys.exit(0 if report["beats_naive"] else 1)


if __name__ == "__main__":
    main()
