"""Train the route-sequence transformer as a serving leg-cost model.

The transformer (models/route_transformer.py) predicts per-leg travel
seconds with ROUTE context — where in the tour a leg sits, what
surrounds it — which the per-edge pricers (road GNN, free-flow physics)
cannot express. This script trains it on random-walk routes over the
EXACT routable graph a server aggregates (RoadRouter's post-bridge edge
set, same contract as scripts/train_gnn.py), evaluates against naive
physics on held-out routes AND held-out hours, and saves a
fingerprinted artifact the router serves automatically
(``optimize/road_router.py:_load_transformer`` →
``properties.leg_cost_model == "transformer"``).

Usage: python scripts/train_transformer.py [--nodes 2048] [--steps 300]
       [--routes 768] [--seq-len 24] [--osm PATH] [--quick] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HELD_OUT_HOURS = (7, 12, 17)  # same non-circular protocol as train_gnn


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--nodes", type=int, default=2048)
    parser.add_argument("--steps", type=int, default=300)
    parser.add_argument("--routes", type=int, default=768)
    parser.add_argument("--seq-len", type=int, default=24)
    parser.add_argument("--batch", type=int, default=128)
    parser.add_argument("--subdivide", type=int, default=0, metavar="K",
                        help="train on OSM-extract topology (K bend nodes "
                             "per street, data/road_graph.subdivide_graph): "
                             "routes become POLYLINE-level edge sequences, "
                             "the regime --seq-len in the hundreds is for")
    parser.add_argument("--osm", default=None, metavar="PATH")
    parser.add_argument("--save", default=None)
    parser.add_argument("--no-save", action="store_true")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--cpu", action="store_true")
    args = parser.parse_args()
    if args.quick:
        args.nodes, args.steps, args.routes = 512, 80, 256
    if args.cpu or os.environ.get("ROUTEST_FORCE_CPU") == "1":
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from routest_tpu.core.cache import enable_compile_cache
    from routest_tpu.data.road_graph import generate_road_graph
    from routest_tpu.models.route_transformer import (RouteTransformer,
                                                      sample_route_sequences)
    from routest_tpu.optimize.road_router import RoadRouter
    from routest_tpu.train.checkpoint import (default_transformer_path,
                                              save_transformer)

    enable_compile_cache()
    if args.osm:
        from routest_tpu.data.osm import load_osm

        router = RoadRouter(graph=load_osm(args.osm), use_gnn=False,
                            use_transformer=False)
        print(f"[1/3] OSM graph {args.osm}: {router.n_nodes} nodes")
    else:
        base = generate_road_graph(n_nodes=args.nodes, k=4, seed=0)
        if args.subdivide:
            from routest_tpu.data.road_graph import subdivide_graph

            base = subdivide_graph(base, bends_per_edge=args.subdivide,
                                   oneway_frac=0.1, seed=0)
        router = RoadRouter(graph=base, use_gnn=False, use_transformer=False)
        print(f"[1/3] graph: {router.n_nodes} nodes"
              + (f" (polyline topology, {args.subdivide} bends/street)"
                 if args.subdivide else ""))
    graph = router.graph_dict()  # post-bridge: the serving fingerprint

    feats, freeflow, targets, mask, hours = sample_route_sequences(
        graph, args.routes, args.seq_len, seed=0, return_hours=True)
    ev_feats, ev_ff, ev_targets, ev_mask, ev_hours, ev_true = \
        sample_route_sequences(
            graph, max(128, args.routes // 4), args.seq_len, seed=1,
            return_hours=True, return_true=True)
    # Non-circular split: training never sees HELD_OUT_HOURS labels.
    keep = ~np.isin(hours, HELD_OUT_HOURS)
    feats, freeflow, targets, mask = (feats[keep], freeflow[keep],
                                      targets[keep], mask[keep])
    print(f"      {len(targets)} train routes "
          f"(hours {sorted(set(HELD_OUT_HOURS))} held out), "
          f"{len(ev_targets)} eval routes")

    model = RouteTransformer()
    params = model.init(jax.random.PRNGKey(0))
    optimizer = optax.adamw(optax.cosine_decay_schedule(3e-4, args.steps),
                            weight_decay=1e-4)
    opt_state = optimizer.init(params)
    positions = jnp.arange(args.seq_len)

    @jax.jit
    def step(params, opt_state, f, ff, y, m):
        loss, grads = jax.value_and_grad(model.loss)(
            params, f, ff, positions, y, m)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    print(f"[2/3] training {args.steps} steps (batch {args.batch})")
    rng = np.random.default_rng(2)
    t0 = time.time()
    for i in range(args.steps):
        idx = rng.integers(0, len(targets), args.batch)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(feats[idx]),
            jnp.asarray(freeflow[idx]), jnp.asarray(targets[idx]),
            jnp.asarray(mask[idx]))
        if (i + 1) % max(1, args.steps // 5) == 0:
            print(f"      step {i + 1}/{args.steps} "
                  f"loss={float(loss):.4f}")
    train_s = time.time() - t0

    pred = np.asarray(model.apply(params, jnp.asarray(ev_feats),
                                  jnp.asarray(ev_ff), positions,
                                  key_mask=jnp.asarray(ev_mask)))

    def rmse(p, y, m):
        m = m.astype(bool)
        return float(np.sqrt(np.mean((p[m] - y[m]) ** 2)))

    held_hours = np.isin(ev_hours, HELD_OUT_HOURS)
    tf_rmse = rmse(pred, ev_targets, ev_mask)
    nv_rmse = rmse(ev_ff, ev_targets, ev_mask)
    tf_h = rmse(pred[held_hours], ev_targets[held_hours],
                ev_mask[held_hours])
    nv_h = rmse(ev_ff[held_hours], ev_targets[held_hours],
                ev_mask[held_hours])
    # Noise floor: observed labels vs the noise-free congestion truth —
    # the best RMSE ANY model can score against observed labels
    # (VERDICT r3 weak #6: 9.69 s was uninterpretable without it).
    floor = rmse(ev_true, ev_targets, ev_mask)
    floor_h = rmse(ev_true[held_hours], ev_targets[held_hours],
                   ev_mask[held_hours])
    print(f"[3/3] eval: transformer {tf_rmse:.2f}s vs naive {nv_rmse:.2f}s "
          f"(floor {floor:.2f}s) | held-out hours: {tf_h:.2f}s vs "
          f"{nv_h:.2f}s (floor {floor_h:.2f}s) | {train_s:.1f}s")

    report = {
        "nodes": int(router.n_nodes),
        "routes": int(len(targets)),
        "seq_len": args.seq_len,
        "steps": args.steps,
        "transformer_rmse_s": tf_rmse,
        "naive_rmse_s": nv_rmse,
        "noise_floor_rmse_s": floor,
        "held_out_hours": list(HELD_OUT_HOURS),
        "transformer_rmse_held_hours_s": tf_h,
        "naive_rmse_held_hours_s": nv_h,
        "noise_floor_held_hours_s": floor_h,
        "vs_floor_held_hours": round(tf_h / max(floor_h, 1e-9), 3),
        "train_seconds": round(train_s, 1),
        "beats_naive": bool(tf_rmse < nv_rmse and tf_h < nv_h),
    }
    if args.subdivide:
        report["polyline_topology"] = {"bends_per_street": args.subdivide}
    if args.osm:
        report["osm"] = args.osm
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = os.path.join(repo, "artifacts", "transformer_report.json")
    # Preserve cross-run sections: the SP seq-scaling curve
    # (scripts/bench_sp_scaling.py) and the polyline-length training run
    # land in the same report under their own keys, so the serving-graph
    # run and the long-sequence run document each other rather than
    # overwriting.
    prior = {}
    if os.path.exists(out):
        try:
            with open(out) as f:
                prior = json.load(f)
        except (ValueError, OSError):
            prior = {}
    if args.subdivide:
        # keep the serving-graph run's top-level metrics intact
        merged = dict(prior)
        merged["polyline_run"] = report
    else:
        # replace top-level metrics, keep the cross-run sections
        merged = {k: v for k, v in prior.items()
                  if k in ("seq_scaling", "polyline_run")}
        merged.update(report)
    with open(out, "w") as f:
        json.dump(merged, f, indent=2)
    print(f"      report → {out}")

    if not args.no_save:
        path = args.save or default_transformer_path()
        save_transformer(path, model, params, graph, seq_len=args.seq_len)
        print(f"      artifact → {path}")
    sys.exit(0 if report["beats_naive"] else 1)


if __name__ == "__main__":
    main()
