"""Dispatch workload end to end → artifacts/dispatch.json.

The ISSUE-16 acceptance record, three parts:

- ``batch_scaling`` — dispatch solves/s through the batched device
  solver (``solve_host_dispatch_batch``, the program behind the
  dispatch batcher) at batch sizes 1→16, each row verified at
  host-oracle parity (``solve_host_dispatch`` per problem, exact trip
  equality). The claim: merged drains beat batch=1 on solves/s — the
  whole point of cross-request coalescing.
- ``corridor_jam`` — a live 2-replica fleet (supervisor + workers +
  gateway + broker bus + probe drivers) under open-loop user load; two
  confirmed dispatches, one riding a named corridor and one far from
  it. The corridor jams (``CongestionScenario`` — slower probe
  observations, never a side channel), the live metric flips, and the
  re-optimization loop must re-solve EXACTLY the affected dispatch and
  push ``plan_update`` over its SSE channel within a bounded window,
  user SLO green throughout.
- ``wrong_plan_fault`` — one replica rolls onto seeded
  ``dispatch.solve:skew`` chaos (well-formed 200 plans, solved over a
  silently perturbed cost matrix). Nothing on the serving path can see
  it; the blackbox prober's ``dispatch`` kind (host re-solve of the
  SAME matrix) must page ``correctness:dispatch``.

Caches (synthetic extract, overlay hierarchy, XLA compiles) persist
under ``--cache-dir`` (default ``artifacts/bench_cache/dispatch``)
across scenarios and battery rounds.

Usage: python scripts/bench_dispatch.py [--quick]
       [--out artifacts/dispatch.json] [--cache-dir DIR]
"""

from __future__ import annotations

import argparse
import http.client
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.parse

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

import bench_probing as bp  # noqa: E402  (Fleet/extract/load harness)

BATCH_SIZES = [1, 2, 4, 8, 16]
N_STOPS = 12
JAM_SPEED_FACTOR = 0.25        # corridor traffic at quarter speed
JAM_WIDTH_M = 1500.0
PLAN_UPDATE_BOUND_S = 120.0
PAGE_BOUND_S = 90.0
# skew=1.0/80: up to 80% per-leg cost error. /40 is NOT enough — the
# probe problem happens to admit a different-order, equal-cost plan at
# that magnitude (the prober correctly judges on cost, and passes);
# /80 lands the served plan measurably worse under the true matrix.
DISPATCH_SKEW_SPEC = "dispatch.solve:skew=1.0/80"
DISPATCH_PROBE_TOL = 0.005


# ── part 1: batch scaling at oracle parity ───────────────────────────


def _problem(rng, n=N_STOPS, windows=False):
    pts = np.round(rng.random((n + 1, 2)) * 60.0, 3)
    dist = np.round(np.sqrt(
        ((pts[:, None] - pts[None]) ** 2).sum(-1)), 3).astype(np.float32)
    demands = rng.integers(1, 4, n).astype(np.float32)
    tw_open = tw_close = None
    if windows:
        tw_open = np.zeros(n, np.float32)
        tw_close = np.full(n, 1e4, np.float32)
    return dict(dist=dist, demands=demands, capacity=7.0,
                max_distance=500.0, tw_open=tw_open, tw_close=tw_close)


def _same_plan(a: dict, b: dict) -> bool:
    return (a["trips"] == b["trips"]
            and a["spill_lane"] == b["spill_lane"]
            and a["unroutable"] == b["unroutable"])


def batch_scaling(quick: bool) -> dict:
    from routest_tpu.optimize.vrp import (solve_host_dispatch,
                                          solve_host_dispatch_batch)

    target_s = 1.5 if quick else 4.0
    rows = []
    for bsz in BATCH_SIZES:
        rng = np.random.default_rng(2026_00 + bsz)
        probs = [_problem(rng, windows=(i % 4 == 3)) for i in range(bsz)]
        args = (
            [p["dist"] for p in probs],
            [p["demands"] for p in probs],
            [p["capacity"] for p in probs],
            [p["max_distance"] for p in probs],
        )
        kw = dict(tw_opens=[p["tw_open"] for p in probs],
                  tw_closes=[p["tw_close"] for p in probs])
        # Oracle first: each problem solved alone on the host path.
        oracles = [solve_host_dispatch(
            p["dist"], p["demands"], p["capacity"], p["max_distance"],
            tw_open=p["tw_open"], tw_close=p["tw_close"]) for p in probs]
        # Warm the (batch, stops) bucket, then estimate reps for the
        # timing window.
        t0 = time.perf_counter()
        results = solve_host_dispatch_batch(*args, **kw)
        warm_s = time.perf_counter() - t0
        parity = all(_same_plan(r, o) for r, o in zip(results, oracles))
        t0 = time.perf_counter()
        est = None
        for _ in range(3):
            solve_host_dispatch_batch(*args, **kw)
        est = (time.perf_counter() - t0) / 3
        reps = max(4, int(round(target_s / max(est, 1e-4))))
        t0 = time.perf_counter()
        for _ in range(reps):
            solve_host_dispatch_batch(*args, **kw)
        elapsed = time.perf_counter() - t0
        rows.append({
            "batch": bsz, "stops": N_STOPS, "reps": reps,
            "solves_per_s": round(bsz * reps / elapsed, 2),
            "ms_per_drain": round(elapsed / reps * 1000, 3),
            "ms_per_solve": round(elapsed / (reps * bsz) * 1000, 3),
            "warm_s": round(warm_s, 3),
            "oracle_parity": bool(parity),
        })
        print(f"  batch={bsz:>2}: {rows[-1]['solves_per_s']:>9} "
              f"solves/s  parity={parity}", flush=True)
    checks = {
        "rows_ge_3": len(rows) >= 3,
        "all_rows_oracle_parity": all(r["oracle_parity"] for r in rows),
        "throughput_scales_with_batch":
            rows[-1]["solves_per_s"] > rows[0]["solves_per_s"],
    }
    return {"rows": rows, "checks": checks,
            "pass": all(checks.values())}


# ── SSE tap: collect plan_update events off a replica's feed ─────────


class SseTap:
    """One ``/api/realtime_feed`` subscription that PARSES events (the
    loadgen ``SseClients`` only counts them): every ``data:`` payload
    is kept, and :meth:`plan_updates` filters the re-opt pushes."""

    def __init__(self, base: str, channel: str) -> None:
        parts = urllib.parse.urlsplit(base)
        self._host, self._port = parts.hostname, parts.port
        self._path = f"/api/realtime_feed?channel={channel}"
        self.channel = channel
        self.events: list = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._sock = None
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        conn = http.client.HTTPConnection(self._host, self._port,
                                          timeout=30.0)
        try:
            conn.request("GET", self._path)
            resp = conn.getresponse()
            if resp.status != 200:
                return
            sock = conn.sock or getattr(
                getattr(resp.fp, "raw", None), "_sock", None)
            if sock is not None:
                sock.settimeout(None)
            self._sock = sock
            buf = b""
            while not self._stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.startswith(b"data:"):
                        continue
                    try:
                        ev = json.loads(line[5:].strip())
                    except ValueError:
                        continue
                    with self._lock:
                        self.events.append(ev)
        except (http.client.HTTPException, OSError):
            pass
        finally:
            conn.close()

    def plan_updates(self) -> list:
        with self._lock:
            return [e for e in self.events
                    if isinstance(e, dict)
                    and e.get("event") == "plan_update"]

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                import socket as _socket

                self._sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        self._thread.join(timeout=5.0)


class CorridorSweep:
    """Deterministic corridor coverage: one synthetic driver per tick
    observing EVERY corridor edge at its scenario-priced speed. The
    random-walk ambiance fleet makes the metric live everywhere; the
    sweep guarantees the jam is *seen* promptly on the edges that
    matter (a real jam is observed by the drivers stuck in it)."""

    def __init__(self, publish, corridor, length_m, road_class,
                 scenario, tick_s: float = 1.0) -> None:
        from routest_tpu.live.probes import DEFAULT_CHANNEL

        self._publish = publish
        self._channel = DEFAULT_CHANNEL
        self._edges = np.asarray(corridor, np.int64)
        self._length = np.asarray(length_m, np.float64)[self._edges]
        self._rc = np.asarray(road_class, np.int64)[self._edges]
        self._scenario = scenario
        self._tick_s = tick_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        from routest_tpu.data.road_graph import true_edge_time_s

        while not self._stop.wait(self._tick_s):
            now = time.time()
            hour = time.localtime(now).tm_hour
            t = true_edge_time_s(
                self._length, self._rc,
                np.full(len(self._edges), hour, np.int64))
            if self._scenario.active(now):
                t = t / self._scenario.speed_factor
            speeds = self._length / np.maximum(t, 1e-6)
            for lo in range(0, len(self._edges), 48):
                obs = [[int(e), round(float(s), 4)]
                       for e, s in zip(self._edges[lo:lo + 48],
                                       speeds[lo:lo + 48])]
                try:
                    self._publish(self._channel, {
                        "t": now, "hour": hour,
                        "driver": f"sweep{lo}", "obs": obs})
                except Exception:
                    return

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


# ── part 2: corridor jam → re-dispatch exactly the affected ──────────


def _seg_dist_m(sites, a, b) -> np.ndarray:
    """Distance (m) from each (lat, lon) site to segment a→b."""
    from routest_tpu.live.probes import corridor_edges  # noqa: F401

    coords = np.asarray(sites, np.float64)
    a = np.asarray(a, np.float64)
    b = np.asarray(b, np.float64)
    lat0 = np.radians((a[0] + b[0]) / 2.0)
    scale = np.asarray([111_194.9, 111_194.9 * np.cos(lat0)])
    p = (coords - a) * scale
    seg = (b - a) * scale
    seg_len2 = float(seg @ seg)
    t = np.clip((p @ seg) / max(seg_len2, 1e-9), 0.0, 1.0)
    return np.sqrt(((p - t[:, None] * seg[None, :]) ** 2).sum(axis=1))


def _dispatch_body(depot, stops, driver: str) -> dict:
    return {
        "source_point": {"lat": float(depot[0]), "lon": float(depot[1])},
        "destination_points": [
            {"lat": float(la), "lon": float(lo), "payload": 1}
            for la, lo in stops],
        "driver_details": {"driver_name": driver, "vehicle_type": "car",
                           "vehicle_capacity": 9,
                           "maximum_distance": 500_000},
        "confirm": True,
        "sim_seed": 3,
    }


def scenario_corridor_jam(extract, cache_dir, rate, quick) -> dict:
    from routest_tpu.data.locations import SEED_LOCATIONS
    from routest_tpu.data.osm import load_osm
    from routest_tpu.live.probes import (CongestionScenario, ProbeFleet,
                                         corridor_edges)
    from routest_tpu.optimize.road_router import RoadRouter
    from routest_tpu.serve.netbus import NetBus

    work = tempfile.mkdtemp(prefix="dispatch-jam-")
    out: dict = {"scenario": "corridor_jam"}
    fleet = bp.Fleet(live=True, extract=extract, cache_dir=cache_dir,
                     work_dir=work)
    load_stop = threading.Event()
    taps, sweep, probe_fleet = [], None, None
    try:
        # Open-loop user load through the gateway for the run's length
        # — the jam is a dispatch-plane incident; the user SLO must not
        # notice it.
        def _load():
            while not load_stop.is_set():
                try:
                    bp.open_loop(fleet.base, rate, 10.0, stop=load_stop)
                except Exception:
                    pass

        load_thread = threading.Thread(target=_load, daemon=True)
        load_thread.start()

        # Corridor geometry: the jam rides a→b; the calm dispatch sits
        # around the seed site FARTHEST from that segment.
        router = RoadRouter(graph=load_osm(extract), use_gnn=False,
                            use_transformer=False)
        g = router.graph_dict()
        a = (SEED_LOCATIONS[2][1], SEED_LOCATIONS[2][2])
        b = (SEED_LOCATIONS[11][1], SEED_LOCATIONS[11][2])
        sites = [(s[1], s[2]) for s in SEED_LOCATIONS]
        far = _seg_dist_m(sites, a, b)
        c = sites[int(np.argmax(far))]
        corridor = corridor_edges(g["node_coords"], g["senders"],
                                  g["receivers"], a, b,
                                  width_m=JAM_WIDTH_M)
        out["corridor"] = {"a": list(a), "b": list(b),
                           "edges": int(len(corridor)),
                           "width_m": JAM_WIDTH_M,
                           "calm_site": list(c),
                           "calm_dist_to_corridor_m":
                               round(float(far.max()), 1)}
        scenario = CongestionScenario(corridor,
                                      speed_factor=JAM_SPEED_FACTOR)
        scenario.set_active(False)

        # Ambiance fleet (random walk, scenario-priced) + the corridor
        # sweep, both over the broker bus the workers ingest from.
        bus_fleet = NetBus(f"tcp://127.0.0.1:{fleet.broker.port}")
        bus_sweep = NetBus(f"tcp://127.0.0.1:{fleet.broker.port}")
        probe_fleet = ProbeFleet(g, fleet._driver_count,
                                 bus_fleet.publish, seed=42,
                                 obs_per_tick=6, scenario=scenario)
        probe_fleet.start(tick_s=1.0)
        sweep = CorridorSweep(bus_sweep.publish, corridor,
                              g["length_m"], g["road_class"], scenario)
        time.sleep(12.0 if quick else 20.0)   # estimates settle

        # Two confirmed dispatches on replica 0 (the registry is
        # per-replica; SSE taps subscribe to the owner directly, while
        # user load keeps flowing through the gateway).
        replica = f"http://127.0.0.1:{fleet.ports[0]}"
        t_ab = np.linspace(0.18, 0.82, 4)
        jam_stops = [(a[0] + t * (b[0] - a[0]), a[1] + t * (b[1] - a[1]))
                     for t in t_ab]
        calm_stops = [(c[0] + 0.004 * (k + 1), c[1] + 0.003 * (k % 2))
                      for k in range(4)]
        taps = [SseTap(replica, "dina-jam"), SseTap(replica, "dina-calm")]
        jam_resp = bp._post(f"{replica}/api/dispatch",
                            _dispatch_body(a, jam_stops, "dina-jam"),
                            timeout=300.0)
        calm_resp = bp._post(f"{replica}/api/dispatch",
                             _dispatch_body(c, calm_stops, "dina-calm"),
                             timeout=300.0)
        jam_id = jam_resp["dispatch_id"]
        calm_id = calm_resp["dispatch_id"]
        out["dispatches"] = {
            "jam": {"id": jam_id, "cost_s": jam_resp["cost"],
                    "epoch": jam_resp["epoch"]},
            "calm": {"id": calm_id, "cost_s": calm_resp["cost"],
                     "epoch": calm_resp["epoch"]}}

        # Clean window: metric keeps flipping from ambient noise; no
        # plan may churn (re-opt's "exactly the degraded" contract).
        time.sleep(10.0)
        pre_jam = [e["dispatch_id"] for t in taps
                   for e in t.plan_updates()]
        out["clean_window_updates"] = pre_jam

        # Jam. Detection = jammed observations → EWMA → customize flip
        # → re-opt tick → batched re-solve → plan_update over SSE.
        t_jam = time.monotonic()
        scenario.set_active(True)
        detect_s = None
        while time.monotonic() - t_jam < PLAN_UPDATE_BOUND_S:
            if any(e["dispatch_id"] == jam_id
                   for e in taps[0].plan_updates()):
                detect_s = round(time.monotonic() - t_jam, 1)
                break
            time.sleep(0.5)
        time.sleep(8.0)   # grace: catch any spurious calm re-solve
        jam_updates = [e for e in taps[0].plan_updates()
                       if e["dispatch_id"] == jam_id]
        stray = ([e["dispatch_id"] for e in taps[1].plan_updates()]
                 + [e["dispatch_id"] for e in taps[0].plan_updates()
                    if e["dispatch_id"] != jam_id])
        out["page"] = {"detect_s": detect_s,
                       "bound_s": PLAN_UPDATE_BOUND_S}
        out["plan_updates"] = {"jam": len(jam_updates), "stray": stray}
        if jam_updates:
            out["first_update_reason"] = jam_updates[0].get("reason")

        # Owner-replica dispatch surface + gateway user SLO.
        out["dispatch_state"] = {
            k: v for k, v in bp._fetch(f"{replica}/api/dispatch",
                                       timeout=30).items()
            if k in ("epoch", "batcher", "reopt")}
        gw_slo = fleet.gw.slo
        if gw_slo is not None:
            gw_slo.tick()
            out["user_slo_state"] = gw_slo.worst_state()
        checks = {
            "clean_before_jam": not pre_jam,
            "plan_update_within_bound": detect_s is not None,
            "exactly_the_affected": bool(jam_updates) and not stray,
            "user_slo_ok": out.get("user_slo_state", "ok") == "ok",
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        load_stop.set()
        for t in taps:
            t.stop()
        if sweep is not None:
            sweep.stop()
        if probe_fleet is not None:
            probe_fleet.stop()
        try:
            load_thread.join(timeout=20)
        except (NameError, RuntimeError):
            pass
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


# ── part 3: wrong-plan fault → dispatch probe pages ──────────────────


def wait_for_dispatch_page(prober, bound_s: float) -> dict:
    t0 = time.monotonic()
    while time.monotonic() - t0 < bound_s:
        obj = prober.slo.snapshot()["objectives"].get(
            "correctness:dispatch")
        if obj and obj["state"] == "page":
            return {"paged": True,
                    "detect_s": round(time.monotonic() - t0, 2)}
        time.sleep(0.2)
    return {"paged": False, "detect_s": None}


def scenario_wrong_plan_fault(extract, cache_dir, rate, quick) -> dict:
    import dataclasses

    work = tempfile.mkdtemp(prefix="dispatch-fault-")
    out: dict = {"scenario": "wrong_plan_fault"}
    fleet = bp.Fleet(live=False, extract=extract, cache_dir=cache_dir,
                     work_dir=work)
    load_stop = threading.Event()
    try:
        # The dispatch probe judges plan cost under the TRUE matrix;
        # the /80 skew's divergence is ~2.4%, so pin the tolerance
        # well under it (and far above f32 noise).
        fleet.prober_cfg = dataclasses.replace(
            fleet.prober_cfg, route_tolerance_rel=DISPATCH_PROBE_TOL)
        prober = fleet.arm_prober()

        def _load():
            while not load_stop.is_set():
                try:
                    bp.open_loop(fleet.base, rate, 10.0, stop=load_stop)
                except Exception:
                    pass

        load_thread = threading.Thread(target=_load, daemon=True)
        load_thread.start()
        deadline = time.time() + (30 if quick else 60)
        while time.time() < deadline:
            snap = prober.snapshot()["probes"]
            if snap.get("dispatch", {}).get("verdict") == "pass":
                break
            time.sleep(1.0)
        out["baseline_verdicts"] = {
            k: v.get("verdict")
            for k, v in prober.snapshot()["probes"].items()}

        victim = fleet.replica_rids()[0]
        faulty_rid = fleet.inject_replacement(
            victim, {"RTPU_CHAOS_SPEC": DISPATCH_SKEW_SPEC,
                     "RTPU_CHAOS_SEED": "5"},
            version="v-wrong-plan")
        out.update({"victim": victim, "faulty_rid": faulty_rid,
                    "chaos_spec": DISPATCH_SKEW_SPEC})
        page = wait_for_dispatch_page(prober, PAGE_BOUND_S)
        out["page"] = dict(page, bound_s=PAGE_BOUND_S)
        out["dispatch_probe"] = prober.snapshot()["probes"].get(
            "dispatch")
        bundles = bp.correctness_bundles(fleet.recorder_dir)
        out["bundle"] = bp.judge_fault_bundle(bundles, faulty_rid)
        gw_slo = fleet.gw.slo
        if gw_slo is not None:
            gw_slo.tick()
            out["user_slo_state"] = gw_slo.worst_state()
        checks = {
            "baseline_dispatch_pass":
                out["baseline_verdicts"].get("dispatch") == "pass",
            "dispatch_probe_paged": bool(page["paged"]),
            "user_slo_ok": out.get("user_slo_state", "ok") == "ok",
        }
        out["checks"] = checks
        out["pass"] = all(checks.values())
    finally:
        load_stop.set()
        try:
            load_thread.join(timeout=20)
        except (NameError, RuntimeError):
            pass
        fleet.stop()
        shutil.rmtree(work, ignore_errors=True)
    return out


# ── record ───────────────────────────────────────────────────────────


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true",
                        help="smaller extract + shorter phases (CI)")
    parser.add_argument("--nodes", type=int, default=6000)
    parser.add_argument("--rate", type=float, default=2.0)
    parser.add_argument("--cache-dir", default=os.path.join(
        REPO, "artifacts", "bench_cache", "dispatch"))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "dispatch.json"))
    parser.add_argument("--scenario", default=None,
                        choices=("batch_scaling", "corridor_jam",
                                 "wrong_plan_fault"),
                        help="run one part (debug)")
    args = parser.parse_args()
    if args.quick:
        args.nodes = min(args.nodes, 4000)

    os.environ.setdefault("ROUTEST_FORCE_CPU", "1")
    import jax

    jax.config.update("jax_platforms", "cpu")
    os.makedirs(args.cache_dir, exist_ok=True)
    os.environ["ROUTEST_HIER_CACHE"] = os.path.join(args.cache_dir,
                                                    "hier")
    from routest_tpu.core.cache import enable_compile_cache

    enable_compile_cache(os.path.join(args.cache_dir, "xla"))

    t0 = time.time()
    record: dict = {}
    checks: dict = {}

    if args.scenario in (None, "batch_scaling"):
        print("[1/4] batch scaling at oracle parity…", flush=True)
        t = time.perf_counter()
        try:
            record["batch_scaling"] = batch_scaling(args.quick)
        except Exception as e:
            record["batch_scaling"] = {
                "pass": False, "rows": [],
                "error": f"{type(e).__name__}: {e}"}
        record["batch_scaling"]["wall_s"] = round(
            time.perf_counter() - t, 1)
        checks["batch_scaling"] = bool(record["batch_scaling"]["pass"])

    scenarios: dict = {}
    if args.scenario in (None, "corridor_jam", "wrong_plan_fault"):
        print(f"[2/4] extract + overlay cache ({args.nodes:,} nodes)…",
              flush=True)
        extract = bp.build_extract(args.nodes, args.cache_dir)
        plan = [
            ("corridor_jam", lambda: scenario_corridor_jam(
                extract, args.cache_dir, args.rate, args.quick)),
            ("wrong_plan_fault", lambda: scenario_wrong_plan_fault(
                extract, args.cache_dir, args.rate, args.quick)),
        ]
        for i, (name, run) in enumerate(plan):
            if args.scenario and name != args.scenario:
                continue
            print(f"[{i + 3}/4] scenario {name}…", flush=True)
            t = time.perf_counter()
            try:
                scenarios[name] = run()
            except Exception as e:
                scenarios[name] = {"scenario": name, "pass": False,
                                   "error": f"{type(e).__name__}: {e}"}
            scenarios[name]["wall_s"] = round(time.perf_counter() - t, 1)
            checks[name] = bool(scenarios[name].get("pass"))
            print(f"  {name}: "
                  f"{'PASS' if checks[name] else 'FAIL'} "
                  f"({scenarios[name]['wall_s']}s)", flush=True)
    record["scenarios"] = scenarios

    try:
        n_cpus = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cpus = os.cpu_count() or 1
    backend = jax.devices()[0].platform
    record.update({
        "generated_unix": int(t0),
        "host": {"cpus": n_cpus, "platform": sys.platform,
                 "backend": backend},
        # Structural caveats (skip reasons are fields, never prose in
        # `note`): solves/s and detection seconds are host-scaled; the
        # invariants (parity per row, merged beats batch=1, exactly the
        # affected re-solved, probe pages) are not.
        "host_caveat": (
            f"cpu-backend record on {n_cpus} core(s): solves/s and "
            "detection latencies are time-shared-host numbers; judge "
            "the structural checks (oracle parity per row, batch>1 "
            "beats batch=1, exactly-the-affected re-dispatch, "
            "dispatch probe paged), not wall-ms"
            if backend != "tpu" else None),
        "skipped": ("tpu dispatch rows: CPU fallback — re-record when "
                    "a tunnel appears (scripts/run_tpu_battery.sh does "
                    "it automatically)" if backend != "tpu" else None),
        "config": {
            "nodes": args.nodes, "rate_rps": args.rate,
            "batch_sizes": BATCH_SIZES, "stops": N_STOPS,
            "jam_speed_factor": JAM_SPEED_FACTOR,
            "jam_width_m": JAM_WIDTH_M,
            "plan_update_bound_s": PLAN_UPDATE_BOUND_S,
            "page_bound_s": PAGE_BOUND_S,
            "dispatch_skew_spec": DISPATCH_SKEW_SPEC,
            "dispatch_probe_tolerance": DISPATCH_PROBE_TOL,
            "cache_dir": args.cache_dir,
            "quick": bool(args.quick),
        },
        "checks": checks,
    })
    if args.scenario:
        record["partial"] = f"--scenario {args.scenario} (debug run)"
    record["all_pass"] = (bool(checks) and all(checks.values())
                          and (args.scenario is not None
                               or len(checks) == 3))
    record["wall_s"] = round(time.time() - t0, 1)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"\n[4/4] checks: "
          + " ".join(f"{k}={'PASS' if v else 'FAIL'}"
                     for k, v in checks.items())
          + f"\n→ {args.out} (all_pass={record['all_pass']}, "
            f"{record['wall_s']}s)", flush=True)
    # _exit, not sys.exit: sim/probe daemon threads racing interpreter
    # teardown must not turn a written verdict into a crash.
    os._exit(0 if record["all_pass"] else 1)


if __name__ == "__main__":
    main()
