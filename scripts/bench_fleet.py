"""Fleet scaling bench: throughput vs replica count + fault injection.

Closes VERDICT r5 weak #5 (all serving-scale evidence was one process
on one core): boots the fleet subsystem (``serve/fleet``) at replica
counts {1, 2, 4} with REAL serving workers, drives the gateway with the
``scripts/load_test.py`` machinery, and records the throughput curve
plus a kill-one-replica-mid-load fault-injection segment to
``artifacts/fleet_scale.json``.

Honesty note: replica scaling needs cores. The artifact records
``host.cpu_count`` and ``host.multi_core``; on a 1-core container the
curve measures gateway overhead + time-slicing, not scaling, and says
so — the ≥1.3× 2-replica criterion binds on multi-core hosts.

Usage: python scripts/bench_fleet.py [--quick] [--replicas 1 2 4]
       [--batch-size 2048] [--fault-seconds 18]
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _load_load_test():
    spec = importlib.util.spec_from_file_location(
        "load_test", os.path.join(REPO, "scripts", "load_test.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post(base, path, payload, timeout=120.0):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(base, path, timeout=10.0):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as resp:
        return json.loads(resp.read())


def boot_fleet(n: int, warm_batch: int):
    """→ (supervisor, gateway, base_url). Real serving workers on the
    hermetic CPU backend; each replica warmed directly so the timed
    phase never pays first-touch costs (load-test methodology)."""
    from routest_tpu.core.config import FleetConfig
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    ports = [_free_port() for _ in range(n)]
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        "ETA_MODEL_PATH": os.path.join(REPO, "artifacts",
                                       "eta_mlp.msgpack"),
    })
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0)
    sup.start()
    if not sup.ready(timeout=300):
        sup.drain(timeout=10)
        raise RuntimeError("fleet workers never became ready")
    for port in ports:  # warm every replica's serving path directly
        base = f"http://127.0.0.1:{port}"
        _post(base, "/api/predict_eta", {
            "summary": {"distance": 10_000}, "weather": "Sunny",
            "traffic": "Medium", "driver_age": 35,
            "pickup_time": "2026-07-29T18:00:00"})
        if warm_batch:
            _post(base, "/api/predict_eta_batch", {
                "distance_m": [1000.0] * warm_batch})
    gw = Gateway([("127.0.0.1", p) for p in ports],
                 FleetConfig(hedge=True, eject_after=3, cooldown_s=1.0,
                             max_inflight=64, queue_depth=256),
                 supervisor=sup)
    httpd = gw.serve("127.0.0.1", 0)
    return sup, gw, f"http://127.0.0.1:{httpd.server_address[1]}"


def fault_injection_segment(sup, gw, base, seconds: float,
                            n_threads: int = 4) -> dict:
    """Steady single-row load; SIGKILL one replica a third of the way
    in; 1-second timeline buckets of ok/err. The gateway's idempotent
    retry should keep client-visible errors near zero while the
    supervisor restarts the victim."""
    buckets: dict = {}
    lock = threading.Lock()
    stop = threading.Event()
    t_start = time.time()

    payload = {"summary": {"distance": 12_000}, "weather": "Stormy",
               "traffic": "High", "driver_age": 40,
               "pickup_time": "2026-07-29T18:00:00"}

    def pump():
        while not stop.is_set():
            sec = int(time.time() - t_start)
            try:
                status, _ = _post(base, "/api/predict_eta", payload,
                                  timeout=30)
                ok = status == 200
            except Exception:
                ok = False
            with lock:
                b = buckets.setdefault(sec, {"ok": 0, "err": 0})
                b["ok" if ok else "err"] += 1

    threads = [threading.Thread(target=pump) for _ in range(n_threads)]
    for t in threads:
        t.start()
    kill_at = seconds / 3.0
    time.sleep(kill_at)
    victim = sup._replicas[0].proc
    victim_pid = victim.pid
    victim.send_signal(signal.SIGKILL)
    kill_sec = int(time.time() - t_start)
    time.sleep(max(0.0, seconds - kill_at))
    stop.set()
    for t in threads:
        t.join()

    timeline = [{"t": t_sec, **buckets[t_sec]}
                for t_sec in sorted(buckets)]
    total_ok = sum(b["ok"] for b in buckets.values())
    total_err = sum(b["err"] for b in buckets.values())
    pre = [b for t_sec, b in sorted(buckets.items()) if t_sec < kill_sec]
    tail = [b for t_sec, b in sorted(buckets.items())
            if t_sec >= max(kill_sec + 2, int(seconds) - 3)]
    pre_rps = (sum(b["ok"] for b in pre) / len(pre)) if pre else 0.0
    tail_rps = (sum(b["ok"] for b in tail) / len(tail)) if tail else 0.0
    snap = gw.snapshot()
    restarted = snap["fleet"].get("restarts", 0) >= 1
    return {
        "seconds": seconds,
        "clients": n_threads,
        "killed_replica": {"id": "r0", "pid": victim_pid,
                           "at_second": kill_sec},
        "requests_ok": total_ok,
        "requests_err": total_err,
        "error_rate": round(total_err / max(1, total_ok + total_err), 4),
        "pre_kill_rps": round(pre_rps, 1),
        "recovered_rps": round(tail_rps, 1),
        "throughput_recovered": bool(tail_rps >= 0.7 * pre_rps),
        "supervisor_restarted_victim": restarted,
        "gateway_retries": snap["fleet"]["retries"],
        "replica_ejections": {rid: r["ejections"]
                              for rid, r in snap["replicas"].items()},
        "timeline": timeline,
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--replicas", type=int, nargs="+",
                        default=[1, 2, 4])
    parser.add_argument("--batch-size", type=int, default=2048,
                        help="OD pairs per predict_eta_batch request")
    parser.add_argument("--batch-requests", type=int, default=10,
                        help="batch requests per client thread")
    parser.add_argument("--batch-threads", type=int, default=4)
    parser.add_argument("--threads", type=int, default=8,
                        help="single-row clients")
    parser.add_argument("--requests", type=int, default=30,
                        help="single-row requests per client")
    parser.add_argument("--fault-seconds", type=float, default=18.0)
    parser.add_argument("--fault-replicas", type=int, default=2,
                        help="replica count for the fault segment")
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "fleet_scale.json"))
    args = parser.parse_args()
    if args.quick:
        args.batch_requests, args.requests = 4, 10
        args.fault_seconds = 9.0

    lt = _load_load_test()
    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)

    curve = []
    fault = None
    for n in args.replicas:
        print(f"[bench_fleet] === {n} replica(s) ===", file=sys.stderr)
        sup, gw, base = boot_fleet(n, warm_batch=args.batch_size)
        try:
            t0 = time.time()
            single, errs1 = lt.run_load([base], args.threads,
                                        args.requests)
            batch, errs2 = lt.run_batch_load([base], args.batch_threads,
                                             args.batch_requests,
                                             args.batch_size)
            snap = gw.snapshot()
            point = {
                "replicas": n,
                "gateway": base,
                "preds_per_s": batch["preds_per_s"],
                "batch": {k: batch[k] for k in
                          ("batch_size", "threads", "requests", "rows",
                           "p50_ms", "p95_ms", "errors") if k in batch},
                "single_row_rps": single["rps"],
                "predict_eta_p95_ms":
                    single.get("predict_eta", {}).get("p95_ms"),
                "client_errors": len(errs1) + len(errs2),
                "gateway_fleet": snap["fleet"],
                "wall_seconds": round(time.time() - t0, 1),
            }
            curve.append(point)
            print(f"[bench_fleet] {n} replica(s): "
                  f"{point['preds_per_s']} preds/s, "
                  f"{point['single_row_rps']} rps single-row",
                  file=sys.stderr)
            if n == args.fault_replicas:
                print("[bench_fleet] fault injection: killing one "
                      "replica mid-load …", file=sys.stderr)
                fault = fault_injection_segment(sup, gw, base,
                                                args.fault_seconds)
                print(f"[bench_fleet] fault: error_rate="
                      f"{fault['error_rate']}, recovered="
                      f"{fault['throughput_recovered']}", file=sys.stderr)
        finally:
            gw.drain(timeout=10)
            sup.drain(timeout=20)

    by_n = {c["replicas"]: c for c in curve}
    scaling = {}
    if 1 in by_n:
        base_tp = by_n[1]["preds_per_s"] or 1.0
        for n, c in sorted(by_n.items()):
            if n != 1:
                scaling[f"x{n}_vs_x1"] = round(
                    (c["preds_per_s"] or 0.0) / base_tp, 3)
    report = {
        "host": {
            "cpu_count": cores,
            "multi_core": cores > 1,
            "note": None if cores > 1 else
            "1-core container: replicas time-share one core, so the "
            "curve measures gateway overhead, not parallel speedup; "
            "the >=1.3x 2-replica criterion binds on multi-core hosts",
        },
        "recorded_unix": int(time.time()),
        "curve": curve,
        "scaling": scaling,
        "fault_injection": fault,
    }
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps({k: v for k, v in report.items()
                      if k != "fault_injection"}, indent=2))
    print(f"[bench_fleet] report → {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
