"""Rollout bench: safe change delivery, measured end to end.

The ISSUE-7 acceptance bar against a REAL fleet (supervisor + serving
worker processes + in-process gateway + rollout controller) under
open-loop loadgen traffic:

- ``hot_swap`` — ≥3 consecutive verified model hot-swaps land on a
  serving replica under load with ZERO client 5xx and the SLO engine
  never paging; then three bad artifacts (corrupt bytes, NaN weights,
  wildly divergent weights) are each REJECTED by the golden-batch gate
  with the old model still serving.
- ``boot_crash`` / ``corrupt_artifact`` / ``slo_regression`` — three
  distinct bad deploys rolled out through the canary state machine,
  each auto-rolled back (crash-loop watch, /api/health verify gate,
  canary-vs-baseline SLO comparison), with blast radius bounded to the
  canary traffic fraction and the rollback decision + offending version
  captured in a flight-recorder bundle (manifest embedded in the
  artifact).
- ``rollout_good`` — a healthy new version canaries, bakes clean, and
  promotes across the fleet with zero client 5xx.

Same host-honesty contract as ``bench_autoscale.py``: a 1-core
container proves the CONTROL machinery (gates, comparisons, rollbacks,
drains), not parallel capacity.

Usage: python scripts/bench_rollout.py [--quick]
       [--scenarios hot_swap boot_crash corrupt_artifact
        slo_regression rollout_good]
       [--out artifacts/rollout.json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import sys
import tempfile
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASE_MODEL = os.path.join(REPO, "artifacts", "eta_mlp.msgpack")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get_json(base, path, timeout=15.0):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return json.loads(r.read())
    except Exception:
        return {}


def _write_bytes_atomic(path: str, blob: bytes) -> None:
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


class ModelForge:
    """Builds the good/bad artifact variants the scenarios deploy,
    from the repo's real trained model."""

    def __init__(self, workdir: str) -> None:
        import jax

        from routest_tpu.train.checkpoint import load_model, save_model

        self._save = save_model
        self._tree_map = jax.tree_util.tree_map
        self.model, self.params = load_model(BASE_MODEL)
        self.workdir = workdir

    def write(self, name: str, fn) -> str:
        path = os.path.join(self.workdir, name)
        self._save(path, self.model, self._tree_map(fn, self.params))
        return path

    def perturbed(self, name: str, scale: float) -> str:
        """A plausible retrain: tiny uniform weight scale."""
        return self.write(name, lambda x: x * (1.0 + scale))

    def nan(self, name: str) -> str:
        import numpy as np

        return self.write(name, lambda x: np.full_like(x, np.nan))

    def divergent(self, name: str) -> str:
        """Corrupted-export proxy: loads, self-checks finite, but the
        golden batch diverges by ~1e6 minutes."""
        return self.write(name, lambda x: x + 1.0e6)

    def corrupt(self, name: str) -> str:
        path = os.path.join(self.workdir, name)
        _write_bytes_atomic(path, b"garbage, not an artifact\n" * 64)
        return path


class SloWatcher:
    """Samples the gateway SLO engine while a scenario runs — the
    'never paged' witness."""

    def __init__(self, gw) -> None:
        self.gw = gw
        self.states = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.gw.slo is not None:
                self.gw.slo.tick()
                self.states.append(self.gw.slo.worst_state())
            self._stop.wait(0.5)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)

    def paged(self) -> bool:
        return "page" in self.states


class LoadArm:
    """Open-loop loadgen traffic running beside a scenario: started,
    then stopped once the scenario's control action settles (the
    schedule is sized generously; unsent arrivals are simply not
    offered)."""

    def __init__(self, base: str, rate: float, duration_s: float,
                 seed: int, zipf_s: float, workers: int) -> None:
        from routest_tpu.loadgen import (RateCurve, ZipfODWorkload,
                                         paced_schedule, run_open_loop)

        self._run_open_loop = run_open_loop
        self.offsets = paced_schedule(RateCurve.constant(rate), duration_s)
        self.requests = ZipfODWorkload(
            s=zipf_s, seed=seed).sequence(len(self.offsets))
        self.base = base
        self.workers = workers
        self.stop = threading.Event()
        self.records = []
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        self.records = self._run_open_loop(
            [self.base], self.offsets, self.requests,
            workers=self.workers, timeout=35.0, stop=self.stop)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self.stop.set()
        self._thread.join(timeout=60)

    def report(self) -> dict:
        from routest_tpu.loadgen import summarize

        return summarize(self.records, max(
            (r.offset_s for r in self.records), default=0.0) or 1.0,
            len(self.records))


def boot_fleet(args, n: int, cache_dir: str, recorder_dir: str,
               model_path: str, reload_sec: float = 0.0):
    """→ (supervisor, gateway, base_url). ``n`` real serving workers on
    version ``v1``; shared XLA cache so replacement boots are cheap."""
    from routest_tpu.core.config import FleetConfig, RecorderConfig
    from routest_tpu.obs.recorder import FlightRecorder, configure_recorder
    from routest_tpu.serve.fleet.gateway import Gateway
    from routest_tpu.serve.fleet.supervisor import ReplicaSupervisor

    configure_recorder(FlightRecorder(RecorderConfig(
        dir=os.path.join(recorder_dir, "gateway"), min_interval_s=0.0)))
    env = dict(os.environ)
    env.update({
        "ROUTEST_FORCE_CPU": "1",
        "ROUTEST_MESH": "0",
        "ROUTEST_WARM_BUCKETS": "0",
        "RTPU_COMPILE_CACHE": cache_dir,
        "ETA_MODEL_PATH": model_path,
        "RTPU_VERSION": "v1",
        "RTPU_RECORDER_DIR": os.path.join(recorder_dir, "workers"),
        "RTPU_RECORDER_MIN_INTERVAL_S": "0",
    })
    if reload_sec > 0:
        env["ROUTEST_RELOAD_SEC"] = str(reload_sec)
    ports = [_free_port() for _ in range(n)]
    sup = ReplicaSupervisor(ports, env=env, cwd=REPO,
                            probe_interval_s=0.5, backoff_base_s=0.2,
                            backoff_cap_s=2.0, version="v1")
    sup.start()
    if not sup.ready(timeout=300):
        sup.drain(timeout=10)
        raise RuntimeError("fleet workers never became ready")
    cfg = FleetConfig(hedge=False, eject_after=3, cooldown_s=1.0,
                      max_inflight=32, queue_depth=64)
    gw = Gateway([("127.0.0.1", p) for p in ports], cfg, supervisor=sup,
                 version="v1")
    httpd = gw.serve("127.0.0.1", 0)
    return sup, gw, f"http://127.0.0.1:{httpd.server_address[1]}"


def shutdown_fleet(sup, gw):
    from routest_tpu.obs.recorder import configure_recorder

    try:
        gw.drain(timeout=5)
    finally:
        sup.drain(timeout=20)
        configure_recorder(None)


def measure_capacity(base: str, seed: int, zipf_s: float,
                     seconds: float) -> float:
    from routest_tpu.loadgen import (KeepAliveClient, ZipfODWorkload,
                                     run_closed_loop, summarize)

    workload = ZipfODWorkload(s=zipf_s, seed=seed)
    client = KeepAliveClient(base, timeout=120.0)
    try:
        for req in workload.sequence(4):
            client.send(req)          # warm the buckets + the cache path
    finally:
        client.close()
    records = run_closed_loop([base], workload.sequence(100_000),
                              workers=16, duration_s=seconds)
    rep = summarize(records, seconds, len(records), loop="closed")
    return max(5.0, rep["achieved_rps"])


def _bundle_manifest(bundle_path):
    if not bundle_path:
        return None
    try:
        with open(os.path.join(bundle_path, "manifest.json")) as f:
            manifest = json.load(f)
        return {"reason": manifest.get("reason"),
                "detail": manifest.get("detail"),
                "counts": manifest.get("counts")}
    except OSError:
        return None


def _swap_counts(base: str) -> dict:
    """rtpu_model_swaps_total by result, summed over replicas (read
    through the gateway's replica-metrics passthrough)."""
    payload = _get_json(base, "/api/metrics?replicas=1", timeout=30.0)
    out = {"accepted": 0, "rejected": 0}
    for rep in (payload.get("replica_metrics") or {}).values():
        fam = (rep.get("registry") or {}).get("rtpu_model_swaps_total")
        for series in (fam or {}).get("series", ()):
            result = series["labels"].get("result")
            if result in out:
                out[result] += int(series["value"])
    return out


# ── scenario: verified hot-swap under load ───────────────────────────

def scenario_hot_swap(args, forge: ModelForge) -> dict:
    cache_dir = tempfile.mkdtemp(prefix="rollout-xla-")
    recorder_dir = tempfile.mkdtemp(prefix="rollout-pm-")
    live_path = os.path.join(forge.workdir, "live.msgpack")
    shutil.copyfile(BASE_MODEL, live_path)
    sup, gw, base = boot_fleet(args, n=1, cache_dir=cache_dir,
                               recorder_dir=recorder_dir,
                               model_path=live_path,
                               reload_sec=args.reload_sec)
    try:
        capacity = measure_capacity(base, args.seed, args.zipf_s,
                                    args.calibrate_s)
        time.sleep(1.0)
        rate = max(4.0, capacity * 0.4)

        def generation() -> int:
            return int(((_get_json(base, "/api/version").get("model")
                         or {}).get("generation")) or -1)

        gen0 = generation()
        swaps = []
        with SloWatcher(gw) as slo, \
                LoadArm(base, rate, args.load_s, args.seed, args.zipf_s,
                        args.workers) as load:
            time.sleep(2.0)
            # ≥3 good swaps: plausible retrains, each verified against
            # the live model's golden outputs before going live.
            for k in range(1, args.swaps + 1):
                src = forge.perturbed(f"good_{k}.msgpack", 1e-4 * k)
                before = generation()
                shutil.copyfile(src, f"{live_path}.stage")
                os.replace(f"{live_path}.stage", live_path)
                deadline = time.time() + 30
                while time.time() < deadline and generation() <= before:
                    time.sleep(0.2)
                swaps.append({"swap": k,
                              "generation": generation(),
                              "landed": generation() > before})
            # Three bad artifacts: each must be rejected with the old
            # generation still serving.
            rejected = []
            for name, src in (
                    ("corrupt_bytes", forge.corrupt("bad_corrupt.bin")),
                    ("nan_weights", forge.nan("bad_nan.msgpack")),
                    ("divergent_weights",
                     forge.divergent("bad_div.msgpack"))):
                before_gen = generation()
                before_rejected = _swap_counts(base)["rejected"]
                shutil.copyfile(src, f"{live_path}.stage")
                os.replace(f"{live_path}.stage", live_path)
                deadline = time.time() + 20
                now_rejected = before_rejected
                while time.time() < deadline \
                        and now_rejected <= before_rejected:
                    time.sleep(0.3)
                    now_rejected = _swap_counts(base)["rejected"]
                rejected.append({
                    "artifact": name,
                    "rejected": now_rejected > before_rejected,
                    "generation_unchanged": generation() == before_gen,
                })
            time.sleep(1.0)
        report = load.report()
        counts = _swap_counts(base)
        health = _get_json(base, "/api/health")
        model_ok = ((health.get("checks") or {}).get("model")
                    or {}).get("status") == "ok"
        versions = gw.version_skew()
        out = {
            "capacity_rps_1_replica": round(capacity, 1),
            "offered_rps": round(rate, 1),
            "initial_generation": gen0,
            "good_swaps": swaps,
            "bad_artifacts": rejected,
            "swap_counts": counts,
            "load": report,
            "slo": {"states_seen": sorted(set(slo.states)),
                    "paged": slo.paged()},
            "versions": versions,
        }
        out["pass"] = bool(
            len(swaps) >= 3
            and all(s["landed"] for s in swaps)
            and counts["accepted"] >= args.swaps
            and counts["rejected"] >= 3
            and all(r["rejected"] and r["generation_unchanged"]
                    for r in rejected)
            and model_ok
            and report["errors"] == 0
            and not slo.paged())
        return out
    finally:
        shutdown_fleet(sup, gw)
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(recorder_dir, ignore_errors=True)


# ── canary rollout scenarios ─────────────────────────────────────────

def _rollout_scenario(args, forge: ModelForge, *, version: str,
                      env: dict, expect_state: str, expect_triggers,
                      chaos_spec: str = "", fraction: float = 0.25,
                      bake_s: float = None, blast_check=None) -> dict:
    from routest_tpu import chaos
    from routest_tpu.core.config import RolloutConfig
    from routest_tpu.serve.fleet.rollout import RolloutController

    cache_dir = tempfile.mkdtemp(prefix="rollout-xla-")
    recorder_dir = tempfile.mkdtemp(prefix="rollout-pm-")
    live_path = os.path.join(forge.workdir, f"base_{version}.msgpack")
    shutil.copyfile(BASE_MODEL, live_path)
    sup, gw, base = boot_fleet(args, n=2, cache_dir=cache_dir,
                               recorder_dir=recorder_dir,
                               model_path=live_path)
    if chaos_spec:
        chaos.configure(chaos.ChaosEngine(spec=chaos_spec,
                                          seed=args.seed))
    try:
        capacity = measure_capacity(base, args.seed, args.zipf_s,
                                    args.calibrate_s)
        time.sleep(1.0)
        rate = max(4.0, capacity * 0.4)
        ctl = RolloutController(sup, gw, RolloutConfig(
            canary_fraction=fraction, canary_replicas=1,
            bake_s=bake_s if bake_s is not None else args.bake_s,
            tick_s=0.25, max_unavailable=1, min_canary_requests=5,
            max_error_rate=0.05, max_error_ratio=3.0,
            latency_threshold_ms=args.latency_ms,
            max_latency_regression=0.25, crash_restarts=2,
            boot_timeout_s=240.0, health_timeout_s=30.0,
            drain_timeout_s=8.0))
        with SloWatcher(gw) as slo, \
                LoadArm(base, rate, args.load_s * 3, args.seed,
                        args.zipf_s, args.workers) as load:
            time.sleep(2.0)
            assert ctl.start(version, env=env)
            final = ctl.wait(timeout=600)
            time.sleep(2.0)
        report = load.report()
        snap = ctl.snapshot()
        rollback = next((h for h in snap["history"]
                         if h.get("event") == "rollback"), None)
        with gw._lock:
            fleet_versions = sorted({r.version for r in gw.replicas})
            fleet_size = len(gw.replicas)
        out = {
            "capacity_rps_1_replica": round(capacity, 1),
            "offered_rps": round(rate, 1),
            "version": version,
            "final_state": final,
            "fleet_versions": fleet_versions,
            "fleet_size": fleet_size,
            "rollback": rollback,
            "bundle": _bundle_manifest(snap.get("last_bundle")),
            "last_verdict": snap.get("last_verdict"),
            "load": report,
            "slo": {"states_seen": sorted(set(slo.states)),
                    "paged": slo.paged()},
            "history": snap["history"],
        }
        checks = [final == expect_state, fleet_size == 2]
        if expect_state == "rolled_back":
            checks += [
                rollback is not None,
                rollback and rollback.get("trigger") in expect_triggers,
                rollback and rollback.get("offending_version") == version,
                out["bundle"] is not None,
                out["bundle"] and out["bundle"]["reason"]
                == "rollout_rollback",
                fleet_versions == ["v1"],
            ]
        else:
            checks += [fleet_versions == [version],
                       report["errors"] == 0]
        if blast_check is not None:
            blast = blast_check(report)
            out["blast_radius"] = blast
            checks.append(blast["bounded"])
        out["pass"] = bool(all(checks))
        return out
    finally:
        if chaos_spec:
            from routest_tpu import chaos as _chaos

            _chaos.configure(None)
        shutdown_fleet(sup, gw)
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(recorder_dir, ignore_errors=True)


def scenario_boot_crash(args, forge: ModelForge) -> dict:
    """The new version's process exits at boot (``replica.boot.<v>``
    chaos, deterministic): the crash-loop watch rolls back before the
    canary ever joins the gateway — client traffic never sees it."""
    return _rollout_scenario(
        args, forge, version="v2-bootcrash",
        env={"RTPU_VERSION": "v2-bootcrash"},
        chaos_spec="replica.boot.v2-bootcrash:error=1.0",
        expect_state="rolled_back",
        expect_triggers={"boot_crash_loop", "boot_timeout"},
        blast_check=lambda rep: {"client_5xx": rep["errors"],
                                 "bounded": rep["errors"] == 0})


def scenario_corrupt_artifact(args, forge: ModelForge) -> dict:
    """The new version points at corrupt model bytes: the worker boots
    (degraded-not-down) but its /api/health model check fails the
    verify gate — rollback before any traffic routes to it."""
    corrupt = forge.corrupt("deploy_corrupt.bin")
    return _rollout_scenario(
        args, forge, version="v3-corrupt",
        env={"RTPU_VERSION": "v3-corrupt", "ETA_MODEL_PATH": corrupt},
        expect_state="rolled_back", expect_triggers={"verify_failed"},
        blast_check=lambda rep: {"client_5xx": rep["errors"],
                                 "bounded": rep["errors"] == 0})


def scenario_slo_regression(args, forge: ModelForge) -> dict:
    """The new version boots healthy but serves with +2.5 s device
    latency (worker-side seeded chaos): only the bake's canary-vs-
    baseline SLO comparison can catch it. Blast radius: the canary
    fraction bounds how much traffic ever saw the slow version — the
    fleet-wide median must stay under the latency threshold."""
    def blast(rep: dict) -> dict:
        lat = rep.get("latency") or {}
        p50 = lat.get("p50_ms")
        return {"p50_ms": p50, "client_5xx": rep["errors"],
                "bounded": bool(p50 is not None
                                and p50 <= args.latency_ms)}

    # Cache off on the bad version: the regression must be visible on
    # every request it serves, not amortized away by the content-
    # addressed cache warming over the Zipf head.
    return _rollout_scenario(
        args, forge, version="v4-slow",
        env={"RTPU_VERSION": "v4-slow",
             "RTPU_CHAOS_SPEC": "device.compute:latency=1.0/2500",
             "RTPU_CHAOS_SEED": str(args.seed),
             "RTPU_FASTLANE_CACHE": "0"},
        expect_state="rolled_back", bake_s=max(args.bake_s * 3, 25.0),
        expect_triggers={"canary_latency", "canary_error_rate",
                         "slo_page"},
        blast_check=blast)


def scenario_rollout_good(args, forge: ModelForge) -> dict:
    """A healthy retrain promotes: canary → clean bake → the whole
    fleet rolls to it, zero client 5xx."""
    v2 = forge.perturbed("deploy_good.msgpack", 2e-4)
    return _rollout_scenario(
        args, forge, version="v2-good",
        env={"RTPU_VERSION": "v2-good", "ETA_MODEL_PATH": v2},
        expect_state="done", expect_triggers=set())


SCENARIOS = {
    "hot_swap": scenario_hot_swap,
    "boot_crash": scenario_boot_crash,
    "corrupt_artifact": scenario_corrupt_artifact,
    "slo_regression": scenario_slo_regression,
    "rollout_good": scenario_rollout_good,
}


def main() -> None:
    from routest_tpu.utils.logging import get_logger

    log = get_logger("routest_tpu.bench_rollout")
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--seed", type=int, default=42)
    parser.add_argument("--zipf-s", type=float, default=1.1)
    parser.add_argument("--workers", type=int, default=48,
                        help="open-loop sender threads")
    parser.add_argument("--swaps", type=int, default=3,
                        help="good hot-swaps to land under load")
    parser.add_argument("--latency-ms", type=float, default=1200.0)
    parser.add_argument("--scenarios", nargs="*", default=None,
                        choices=sorted(SCENARIOS))
    parser.add_argument("--out", default=os.path.join(
        REPO, "artifacts", "rollout.json"))
    args = parser.parse_args()
    if args.quick:
        args.calibrate_s = 3.0
        args.load_s = 45.0
        args.bake_s = 8.0
        args.reload_sec = 0.25
    else:
        args.calibrate_s = 5.0
        args.load_s = 75.0
        args.bake_s = 12.0
        args.reload_sec = 0.25

    workdir = tempfile.mkdtemp(prefix="rollout-models-")
    forge = ModelForge(workdir)
    results = {}
    try:
        for name in (args.scenarios or list(SCENARIOS)):
            log.info("rollout_scenario_started", scenario=name)
            t0 = time.time()
            try:
                results[name] = SCENARIOS[name](args, forge)
            except Exception as e:
                results[name] = {"error": f"{type(e).__name__}: {e}",
                                 "pass": False}
                log.error("rollout_scenario_failed", scenario=name,
                          error=f"{type(e).__name__}: {e}")
            results[name]["wall_s"] = round(time.time() - t0, 1)
            log.info("rollout_scenario_finished", scenario=name,
                     ok=results[name].get("pass"),
                     wall_s=results[name]["wall_s"])
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    record = {
        "generated_unix": int(time.time()),
        "host": {
            "cpu_count": cores,
            "multi_core": cores > 1,
            "note": None if cores > 1 else
            "1-core container: replicas time-share the core, so these "
            "scenarios prove the change-delivery machinery (verified "
            "swaps, gates, cohort comparison, rollbacks, drains) — "
            "capacity effects bind on multi-core hosts",
        },
        "loadgen": {"zipf_s": args.zipf_s, "seed": args.seed,
                    "workers": args.workers,
                    "open_loop": "latency measured from intended send "
                                 "time (coordinated-omission-correct)"},
        "scenarios": results,
        "all_pass": all(r.get("pass") for r in results.values()),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2, default=str)
    log.info("rollout_written", path=args.out,
             all_pass=record["all_pass"])
    print(json.dumps({k: (v if k != "scenarios" else {
        n: {kk: vv for kk, vv in s.items()
            if kk in ("pass", "wall_s", "final_state", "rollback",
                      "swap_counts", "blast_radius", "slo", "error")}
        for n, s in v.items()}) for k, v in record.items()},
        indent=2, default=str))


if __name__ == "__main__":
    main()
