-- routest_tpu executable schema (PostgreSQL / Supabase).
--
-- Canonical DDL for the persistence layer (serve/store.py). Mirrors the
-- reference's Laravel migrations --
-- locations:      backend/laravel/database/migrations/2025_08_12_144039_create_locations_table.php:10-16
-- route_requests: ...144349_create_route_requests_table.php:10-18
-- route_results:  ...144521_create_route_results_table.php:10-20
-- -- PLUS the runtime drift columns the reference's Flask service writes
-- outside its own migrations (Flaskr/routes.py:148-155,167-176):
-- route_requests.engine / vehicle_id / driver_age and
-- route_results.geometry / eta_minutes_ml / eta_completion_time_ml.
-- Apply to a fresh database with:  psql "$DATABASE_URL" -f schema.sql

BEGIN;

CREATE TABLE IF NOT EXISTS locations (
  id         uuid PRIMARY KEY,
  name       text NOT NULL,
  latitude   numeric(9, 6) NOT NULL,
  longitude  numeric(9, 6) NOT NULL,
  created_at timestamptz NOT NULL DEFAULT now()
);

CREATE TABLE IF NOT EXISTS route_requests (
  id           uuid PRIMARY KEY DEFAULT gen_random_uuid(),
  origin_id    uuid NOT NULL REFERENCES locations (id) ON DELETE CASCADE,
  stops        jsonb NOT NULL DEFAULT '[]'::jsonb,
  request_time timestamptz NOT NULL DEFAULT now(),
  status       text NOT NULL DEFAULT 'pending',
  -- runtime drift columns (written by the optimizer service)
  engine       text,
  vehicle_id   text,
  driver_age   numeric(5, 2)
);

CREATE TABLE IF NOT EXISTS route_results (
  id              uuid PRIMARY KEY DEFAULT gen_random_uuid(),
  request_id      uuid NOT NULL REFERENCES route_requests (id) ON DELETE CASCADE,
  optimized_order jsonb NOT NULL DEFAULT '[]'::jsonb,
  total_distance  numeric(10, 2),
  total_duration  numeric(10, 2),
  legs            jsonb NOT NULL DEFAULT '[]'::jsonb,
  created_at      timestamptz NOT NULL DEFAULT now(),
  -- runtime drift columns (written by the optimizer service)
  geometry        jsonb,
  eta_minutes_ml  numeric(10, 2),
  eta_completion_time_ml timestamptz
);

-- History reads are newest-first with an embedded-result join
-- (serve/store.py list_history / Flaskr/routes.py:193-204).
CREATE INDEX IF NOT EXISTS route_requests_request_time_idx
  ON route_requests (request_time DESC);
CREATE INDEX IF NOT EXISTS route_results_request_id_idx
  ON route_results (request_id);

-- Seed: the 21 canonical Metro Manila sites (data/locations.py;
-- reference seeder LocationsTableSeeder.php:13-35). Deterministic
-- uuid5 ids, identical to the in-memory store's.
INSERT INTO locations (id, name, latitude, longitude) VALUES
  ('ca61450b-e966-53ad-a248-367ae6b6a430', 'Main Warehouse - Mandaluyong', 14.5836, 121.0409),
  ('98f8b35f-63d6-5f8c-8faf-cdcaa03d18b3', 'SM Mall of Asia', 14.5352, 120.9822),
  ('4bd234d0-934d-5e29-9d0a-b639fdf94f5e', 'Greenbelt Mall', 14.5516, 121.0233),
  ('da1a989e-2f47-5c62-9273-c3adbcb4147d', 'SM Megamall', 14.5833, 121.0567),
  ('bdf0e64f-914e-543f-ba90-cb8feca6f470', 'Market! Market!', 14.5536, 121.0546),
  ('eb1549f3-21af-5711-a176-43dbf7e091b8', 'Robinsons Galleria', 14.5896, 121.0614),
  ('447d44d9-14e5-5b16-aa1a-5be7b23eb7c0', 'SM North EDSA', 14.6556, 121.0313),
  ('51a183b9-cd02-579b-84b9-9aea0dbd61a7', 'Trinoma Mall', 14.6537, 121.0321),
  ('71aa6c0f-6bd6-54c1-bc7e-cecd7ebecb30', 'Gateway Mall', 14.6206, 121.0526),
  ('5c0bc6cc-f0e0-5ec3-a03f-58f0279659d1', 'SM City Manila', 14.5881, 120.9814),
  ('1e876957-1d88-5ee4-a13b-3f82897e9956', 'Lucky Chinatown Mall', 14.6054, 120.9734),
  ('fe52bfe2-09b7-5ba5-905c-011bf09089d2', 'SM Aura Premier', 14.5456, 121.0559),
  ('d3b9f0ff-6289-5770-a2d7-da4c1b9e1b36', 'Robinsons Place Manila', 14.5730, 120.9820),
  ('b54ce262-67d3-5478-825f-106d2dfeaf22', 'Ayala Malls Vertis North', 14.6543, 121.0327),
  ('7ca09632-5256-53a0-9be3-0d80a94b2bd9', 'Fisher Mall', 14.6300, 121.0045),
  ('4caf382e-4fbe-5060-ad12-9adaf234123d', 'SM City Sta. Mesa', 14.6031, 121.0275),
  ('224afa90-b58b-52e8-8911-f19072ee18d7', 'Alabang Town Center', 14.4269, 121.0314),
  ('340ee4d8-ab3b-57fc-be9e-10273639f11d', 'Festival Mall Alabang', 14.4143, 121.0438),
  ('5d2aab15-000e-5ab2-b8b2-54db4afdbc3b', 'Eastwood Mall', 14.6101, 121.0791),
  ('b0b7c7e5-8a49-588d-969a-dc88d96c576b', 'Robinsons Magnolia', 14.6162, 121.0336),
  ('36a4c35c-94d9-59b2-8f7b-508ef6d13009', 'Venice Grand Canal Mall', 14.5404, 121.0530)
ON CONFLICT (id) DO NOTHING;

COMMIT;
